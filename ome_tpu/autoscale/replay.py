"""Open-loop trace replay: the load half of the closed loop.

Replays a trace (trace.py) against an endpoint with the ORIGINAL
inter-arrival gaps — open-loop, i.e. arrivals never wait for earlier
responses, so queueing delay shows up as queueing delay instead of
being absorbed by a closed-loop client (the coordinated-omission
trap). Each request runs on its own thread: sleep until its arrival
offset, POST /v1/completions with stream=true, and measure
CLIENT-SIDE TTFT (first SSE delta), TPOT, and e2e, collecting the
full text for greedy byte-comparison.

``report()`` folds the per-request results into percentiles and SLO
attainment — the JSON the bench `replay` subcommand and
``scripts/replay.py`` print, and the numbers the autoscale soak
judges the controller by.
"""

from __future__ import annotations

import argparse
import json
import logging
import pathlib
import sys
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence

from . import trace as trace_mod
from .trace import TraceRequest

log = logging.getLogger("ome.autoscale")


@dataclass
class ReplayResult:
    trace_id: Optional[str]
    arrival: float
    prompt: str
    max_tokens: int
    temperature: float
    priority: Optional[str] = None
    status: Optional[int] = None
    ttft_s: Optional[float] = None
    tpot_s: Optional[float] = None
    e2e_s: Optional[float] = None
    output_tokens: int = 0
    text: str = ""
    finish_reason: Optional[str] = None
    error: Optional[str] = None
    # router fronts tried before a response arrived (HA failover)
    failovers: int = 0

    @property
    def ok(self) -> bool:
        return self.status == 200 and self.error is None


def _stream_one(urls, result: ReplayResult,
                timeout: float) -> None:
    """One request against an endpoint, or a list of router replicas
    tried in order: a transport failure BEFORE any response bytes
    (connection refused, reset — the front is dead) fails over to the
    next URL; once a status line has arrived the request is never
    retried, because retrying a request some router already answered
    is how a client manufactures duplicates (docs/router-ha.md)."""
    if isinstance(urls, str):
        urls = [urls]
    payload = {
        "prompt": result.prompt, "max_tokens": result.max_tokens,
        "temperature": result.temperature, "stream": True}
    headers = {"Content-Type": "application/json"}
    if result.priority:
        # class in BOTH forms: the payload survives router
        # passthrough, the header is what the engine prefers
        payload["priority"] = result.priority
        headers["X-OME-Priority"] = result.priority
    body = json.dumps(payload).encode()
    t0 = time.monotonic()
    first = last = None
    for attempt, url in enumerate(urls):
        req = urllib.request.Request(
            url + "/v1/completions", data=body, headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                result.status = resp.status
                for raw in resp:
                    line = raw.decode("utf-8", "replace").strip()
                    if not line.startswith("data:"):
                        continue
                    data = line[5:].strip()
                    if data == "[DONE]":
                        break
                    try:
                        chunk = json.loads(data)
                    except ValueError:
                        continue
                    for choice in chunk.get("choices", []):
                        text = choice.get("text") or choice.get(
                            "delta", {}).get("content")
                        if text:
                            now = time.monotonic()
                            if first is None:
                                first = now
                            last = now
                            result.output_tokens += 1
                            result.text += text
                        fin = choice.get("finish_reason")
                        if fin:
                            result.finish_reason = fin
        except urllib.error.HTTPError as e:
            result.status = e.code
            result.error = e.read().decode("utf-8", "replace")[:200]
            e.close()
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            result.error = f"{type(e).__name__}: {e}"
            if result.status is None and attempt + 1 < len(urls):
                result.failovers += 1
                continue
        break
    end = time.monotonic()
    result.e2e_s = round(end - t0, 6)
    if first is not None:
        result.ttft_s = round(first - t0, 6)
        if result.output_tokens > 1 and last is not None:
            result.tpot_s = round(
                (last - first) / (result.output_tokens - 1), 6)


def replay(url, trace: Sequence[TraceRequest],
           timeout: float = 120.0, prompt_seed: int = 0,
           on_result: Optional[Callable[[ReplayResult], None]] = None
           ) -> List[ReplayResult]:
    """Replay ``trace`` against ``url`` (router or engine; a LIST of
    URLs spreads arrivals round-robin across N router replicas with
    client-side failover), honoring arrival offsets; blocks until
    every request has an outcome."""
    urls = [url] if isinstance(url, str) else list(url)
    urls = [u.rstrip("/") for u in urls]
    t0 = time.monotonic()
    results = [ReplayResult(trace_id=r.trace_id, arrival=r.arrival,
                            prompt=r.prompt_text(prompt_seed),
                            max_tokens=r.max_tokens,
                            temperature=r.temperature,
                            priority=getattr(r, "priority", None))
               for r in trace]

    def one(i: int, r: ReplayResult):
        delay = t0 + r.arrival - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        k = i % len(urls)
        _stream_one(urls[k:] + urls[:k], r, timeout)
        if on_result is not None:
            on_result(r)

    threads = [threading.Thread(target=one, args=(i, r), daemon=True)
               for i, r in enumerate(results)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout + 60.0)
    return results


def _pct(xs: List[float], p: float) -> Optional[float]:
    if not xs:
        return None
    xs = sorted(xs)
    i = min(len(xs) - 1, int(round(p / 100 * (len(xs) - 1))))
    return round(xs[i], 6)


def _stats(results: Sequence[ReplayResult], slo_ttft_s: float,
           slo_e2e_s: Optional[float]) -> dict:
    ok = [r for r in results if r.ok]
    ttfts = [r.ttft_s for r in ok if r.ttft_s is not None]
    tpots = [r.tpot_s for r in ok if r.tpot_s is not None]
    e2es = [r.e2e_s for r in ok if r.e2e_s is not None]
    ttft_ok = sum(1 for t in ttfts if t <= slo_ttft_s)
    out = {
        "requests": len(results),
        "completed": len(ok),
        "errors": len(results) - len(ok),
        "failovers": sum(r.failovers for r in results),
        "output_tokens": sum(r.output_tokens for r in ok),
        "ttft_p50_s": _pct(ttfts, 50),
        "ttft_p95_s": _pct(ttfts, 95),
        "ttft_p99_s": _pct(ttfts, 99),
        "tpot_p50_s": _pct(tpots, 50),
        "e2e_p50_s": _pct(e2es, 50),
        "e2e_p99_s": _pct(e2es, 99),
        "slo_ttft_s": slo_ttft_s,
        "slo_ttft_attainment": (round(ttft_ok / len(ttfts), 4)
                                if ttfts else None),
    }
    if slo_e2e_s is not None:
        e2e_ok = sum(1 for t in e2es if t <= slo_e2e_s)
        out["slo_e2e_s"] = slo_e2e_s
        out["slo_e2e_attainment"] = (round(e2e_ok / len(e2es), 4)
                                     if e2es else None)
    return out


def report(results: Sequence[ReplayResult],
           slo_ttft_s: float = 2.0,
           slo_e2e_s: Optional[float] = None) -> dict:
    """Percentiles + SLO attainment over a replay's results. When any
    request carried a priority class, the report also breaks the same
    stats out per class under ``classes`` — the view that shows a
    batch flood hurting batch latency while interactive holds."""
    out = _stats(results, slo_ttft_s, slo_e2e_s)
    by_class: dict = {}
    for r in results:
        if r.priority is not None:
            by_class.setdefault(r.priority, []).append(r)
    if by_class:
        out["classes"] = {
            cls: _stats(rs, slo_ttft_s, slo_e2e_s)
            for cls, rs in sorted(by_class.items())}
    return out


def slo_section(results: Sequence[ReplayResult], spec) -> dict:
    """Client-observed SLO attainment against an ``SLOSpec`` — the
    replay half of the sim-vs-real parity contract (docs/slo.md):
    per-(class, objective) good/total counts that ``GET /slo`` on
    the router must match within +-1 request on a clean run.
    Latency objectives count completed requests (the population the
    engine histograms observe); availability counts every answered
    request as good unless it failed server-side (5xx, timeout,
    transport error, aborted stream)."""
    from ..priority import DEFAULT_PRIORITY
    by_class: dict = {}
    for r in results:
        by_class.setdefault(r.priority or DEFAULT_PRIORITY,
                            []).append(r)
    metric = {"ttft": lambda r: r.ttft_s,
              "e2e": lambda r: r.e2e_s,
              "tpot": lambda r: r.tpot_s}
    out: dict = {}
    for cls in sorted(spec.classes):
        rs = by_class.get(cls, [])
        cls_out: dict = {}
        for obj in spec.classes[cls]:
            if obj.kind == "availability":
                good = sum(
                    1 for r in rs
                    if r.status is not None and r.status < 500
                    and not (r.status == 200 and r.error is not None))
                total = len(rs)
            else:
                get = metric.get(obj.name)
                if get is None:  # not client-measurable (queue_wait)
                    continue
                xs = [x for x in (get(r) for r in rs if r.ok)
                      if x is not None]
                good = sum(1 for x in xs if x <= obj.threshold_s)
                total = len(xs)
            cls_out[obj.name] = {
                "good": good, "total": total,
                "target": obj.target,
                "attainment": (round(good / total, 6)
                               if total else None),
                "budget_consumed": (round(
                    (total - good) / (total * obj.budget), 6)
                    if total else 0.0),
            }
        out[cls] = cls_out
    return out


# -- CLI -------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="replay",
        description="Replay a request trace (engine reqlog, saved "
                    "trace file, or seeded synthetic) against an "
                    "OpenAI-compatible endpoint with original "
                    "inter-arrival gaps; prints a one-line JSON SLO "
                    "report (docs/autoscaling.md). With --topology N "
                    "it spawns its own router + N CPU engines first.")
    p.add_argument("--url", action="append", default=None,
                   help="endpoint to replay against (router or "
                        "engine); repeatable — extra URLs are "
                        "failover fronts tried on transport failure "
                        "(docs/router-ha.md); omit with --topology "
                        "to self-spawn")
    p.add_argument("--topology", type=int, default=0, metavar="N",
                   help="spawn a router + N engine subprocesses and "
                        "replay against them (CI / laptop mode)")
    p.add_argument("--trace", default=None,
                   help="trace source: a save_trace JSONL or an "
                        "engine reqlog (schema v1 or v2)")
    p.add_argument("--seed", type=int, default=0,
                   help="synthetic trace seed (used when --trace is "
                        "not given)")
    p.add_argument("--requests", type=int, default=20)
    p.add_argument("--base-rate", type=float, default=3.0)
    p.add_argument("--burst-factor", type=float, default=4.0)
    p.add_argument("--compress", type=float, default=1.0,
                   help="time-compression factor (>1 replays faster)")
    p.add_argument("--amplify", type=int, default=1,
                   help="duplicate requests in the busiest window "
                        "this many times")
    p.add_argument("--slo-ttft-p99", type=float, default=2.0)
    p.add_argument("--slo-e2e-p99", type=float, default=None)
    p.add_argument("--slo-spec", default=None,
                   help="SLO spec JSON (config/slo.json format): "
                        "adds a per-class 'slo' section of "
                        "client-observed attainment + budget burn "
                        "to the report (docs/slo.md)")
    p.add_argument("--timeout", type=float, default=120.0)
    p.add_argument("--save-trace", default=None,
                   help="also write the (transformed) trace to this "
                        "path for re-replay")
    p.add_argument("--model-dir", default=None)
    p.add_argument("--max-slots", type=int, default=2)
    p.add_argument("--kv-block", type=int, default=16)
    p.add_argument("--kv-blocks", type=int, default=40)
    p.add_argument("--base-dir", default=None,
                   help="scratch dir for --topology logs (default: "
                        "fresh temp dir)")
    return p


def _load_trace_arg(args) -> List[TraceRequest]:
    if args.trace:
        path = pathlib.Path(args.trace)
        try:
            tr = trace_mod.load_trace(path)
        except (KeyError, ValueError):
            tr = trace_mod.load_reqlog(path)
        if not tr:
            raise SystemExit(f"no replayable records in {path}")
    else:
        tr = trace_mod.synthetic_trace(
            args.seed, n=args.requests, base_rate=args.base_rate,
            burst_factor=args.burst_factor)
    if args.amplify > 1:
        tr = trace_mod.amplify_bursts(tr, args.amplify,
                                      seed=args.seed)
    if args.compress != 1.0:
        tr = trace_mod.compress(tr, args.compress)
    return tr


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    if not args.url and not args.topology:
        build_parser().error("need --url or --topology N")
    tr = _load_trace_arg(args)
    if args.save_trace:
        trace_mod.save_trace(tr, args.save_trace)

    cleanup = False
    base_dir = args.base_dir
    if args.topology and base_dir is None:
        import tempfile
        base_dir = tempfile.mkdtemp(prefix="ome-replay-")
        cleanup = True

    pool = None
    router = None
    try:
        url = args.url
        if args.topology:
            from ..chaos import ManagedProc, free_port
            from .pool import EnginePool
            base = pathlib.Path(base_dir)
            model_dir = args.model_dir
            if model_dir is None:
                model_dir = str(base / "model")
                pathlib.Path(model_dir).mkdir(parents=True,
                                              exist_ok=True)

            def engine_args(port, name, journal_dir):
                return ["--model-dir", model_dir, "--random-weights",
                        "--dtype", "float32", "--host", "127.0.0.1",
                        "--port", str(port),
                        "--max-slots", str(args.max_slots),
                        "--kv-block", str(args.kv_block),
                        "--kv-blocks", str(args.kv_blocks),
                        "--prefix-cache-mb", "8",
                        "--journal", str(journal_dir),
                        "--journal-fsync", "always"]

            pool = EnginePool("engine", None, engine_args, base)
            for _ in range(args.topology):
                pool.spawn()
            rport = free_port()
            rargs = ["--bind", "127.0.0.1", "--port", str(rport),
                     "--policy", "round_robin",
                     "--health-interval", "1.0"]
            for u in pool.member_urls():
                rargs += ["--backend", u]
            router = ManagedProc("router", "router", rargs, rport,
                                 base / "router.log")
            router.start()
            router.wait_ready()
            url = router.url

        results = replay(url, tr, timeout=args.timeout,
                         prompt_seed=args.seed)
        rep = report(results, slo_ttft_s=args.slo_ttft_p99,
                     slo_e2e_s=args.slo_e2e_p99)
        if args.slo_spec:
            from ..slo import load as load_slo
            rep["slo"] = slo_section(results,
                                     load_slo(args.slo_spec))
        rep["endpoint"] = (url if isinstance(url, str)
                           else url[0] if len(url) == 1 else url)
        print(json.dumps(rep, separators=(",", ":"), default=str))
        sys.stdout.flush()
        return 0 if rep["errors"] == 0 else 1
    finally:
        if pool is not None:
            pool.stop_all()
        if router is not None:
            router.stop()
        if cleanup:
            import shutil
            shutil.rmtree(base_dir, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
