"""Prometheus text-exposition client for the scaling controller.

The controller decides from the SAME metrics a human would read on a
dashboard — the engines' `ome_engine_ttft_seconds` /
`ome_engine_queue_wait_seconds` histograms, the KV-utilization gauge,
and the router's per-backend gauges — so there is no privileged side
channel to drift from the observable truth.

Histograms are cumulative since process start; a controller wants the
RECENT distribution. ``HistogramWindow`` keeps the previous scrape's
cumulative buckets per (backend, family) and differences them, which
yields the distribution of observations BETWEEN two scrapes; p99 is
estimated by linear interpolation inside the bucket containing the
target rank (the standard histogram_quantile estimator). A counter
reset (engine restart) makes deltas negative — the window discards
that sample and re-bases, same discipline as chaos.MetricsWatch.
"""

from __future__ import annotations

import math
import re
from typing import Callable, Dict, List, Optional, Tuple

from ..chaos import _http

_SAMPLE_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>\S+)$')
_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def parse_exposition(text: str) -> Dict[str, float]:
    """Exposition body -> {'name{labels}': value} (labels verbatim,
    in source order — the same keying chaos.scrape_metrics uses)."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        labels = m.group("labels")
        key = m.group("name") + ("{" + labels + "}" if labels else "")
        out[key] = value
    return out


def split_key(key: str) -> Tuple[str, Dict[str, str]]:
    """'name{a="x",b="y"}' -> ('name', {'a': 'x', 'b': 'y'})."""
    name, brace, rest = key.partition("{")
    if not brace:
        return name, {}
    return name, {m.group(1): m.group(2)
                  for m in _LABEL_RE.finditer(rest[:-1])}


def fetch_metrics(url: str, timeout: float = 5.0) -> Dict[str, float]:
    """Scrape ``url``/metrics into a parsed sample dict."""
    status, body = _http(url.rstrip("/") + "/metrics", timeout=timeout)
    if status != 200:
        raise OSError(f"/metrics answered {status} at {url}")
    if isinstance(body, bytes):
        body = body.decode("utf-8", errors="replace")
    elif not isinstance(body, str):
        body = str(body)
    return parse_exposition(body)


def bucket_counts(samples: Dict[str, float], family: str,
                  label_filter: Optional[Dict[str, str]] = None
                  ) -> List[Tuple[float, float]]:
    """Cumulative (upper_bound, count) pairs for one histogram
    family, summed across label children, sorted by bound (+Inf
    last). ``label_filter`` restricts to children matching every
    given label pair (e.g. {"class": "interactive"} narrows a
    per-class histogram to one tenant class)."""
    acc: Dict[float, float] = {}
    prefix = family + "_bucket"
    for key, value in samples.items():
        name, labels = split_key(key)
        if name != prefix or "le" not in labels:
            continue
        if label_filter and any(labels.get(k) != v
                                for k, v in label_filter.items()):
            continue
        le = labels["le"]
        bound = math.inf if le == "+Inf" else float(le)
        acc[bound] = acc.get(bound, 0.0) + value
    return sorted(acc.items(), key=lambda kv: kv[0])


def quantile_from_buckets(buckets: List[Tuple[float, float]],
                          q: float) -> Optional[float]:
    """histogram_quantile over cumulative buckets: find the bucket
    holding rank q*count, interpolate linearly inside it. The +Inf
    bucket clamps to the last finite bound (Prometheus convention).

    Sentinel: returns None — never NaN, never a division error —
    when there is no estimate at all: an empty list, an all-zero
    window (total <= 0), or a +Inf-only window (every observation
    beyond every finite bound, so no finite bound to clamp to)."""
    if not buckets:
        return None
    total = buckets[-1][1]
    if total <= 0:
        return None
    rank = q * total
    prev_bound, prev_count = 0.0, 0.0
    for bound, count in buckets:
        if count >= rank:
            if math.isinf(bound):
                # rank falls beyond every finite bound: clamp to the
                # last finite bound; with no finite bucket at all
                # (+Inf-only window) there is nothing to clamp to
                return prev_bound if len(buckets) > 1 else None
            if count == prev_count:
                return bound
            frac = (rank - prev_count) / (count - prev_count)
            return prev_bound + (bound - prev_bound) * frac
        prev_bound, prev_count = (0.0 if math.isinf(bound) else bound,
                                  count)
    return buckets[-1][0] if not math.isinf(buckets[-1][0]) else None


class HistogramWindow:
    """Windowed quantiles for one histogram family across scrapes.

    ``update(source, samples)`` ingests a scrape for one source
    (backend URL); ``quantile(q)`` answers over the observations that
    arrived between the previous update and this one, across ALL
    sources. Counter resets re-base silently. ``labels`` narrows the
    family to matching children (per-class SLO windows).

    ``clock`` is optional and injection-only — the window itself
    keeps NO hidden wall-clock default. When a clock is injected
    (the controller passes its own, real or virtual), every update
    is stamped and ``staleness(source)`` answers how old a source's
    latest scrape is in that clock's units; without one, the window
    is purely scrape-ordered, exactly as before."""

    def __init__(self, family: str,
                 labels: Optional[Dict[str, str]] = None,
                 clock: Optional[Callable[[], float]] = None):
        self.family = family
        self.labels = dict(labels) if labels else None
        self.clock = clock
        self._prev: Dict[str, List[Tuple[float, float]]] = {}
        self._window: Dict[str, List[Tuple[float, float]]] = {}
        self._updated_at: Dict[str, float] = {}
        self._incarnation: Dict[str, object] = {}

    def update(self, source: str, samples: Dict[str, float],
               incarnation: Optional[object] = None) -> None:
        """Ingest one scrape. ``incarnation`` (engine restart
        counter, when the source exposes one) forces a re-base when
        it changes: a restarted engine's counters restart from zero
        and can grow PAST the pre-restart values by the next scrape,
        which the counts-went-backwards check alone cannot see — the
        delta would silently mix pre- and post-restart windows."""
        cur = bucket_counts(samples, self.family, self.labels)
        prev = self._prev.get(source)
        self._prev[source] = cur
        if self.clock is not None:
            self._updated_at[source] = self.clock()
        rebased = (incarnation is not None
                   and incarnation != self._incarnation.get(source))
        if incarnation is not None:
            self._incarnation[source] = incarnation
        if prev is None or rebased or len(prev) != len(cur):
            self._window.pop(source, None)
            return
        delta = []
        for (b_cur, c_cur), (b_prev, c_prev) in zip(cur, prev):
            if b_cur != b_prev or c_cur < c_prev:
                self._window.pop(source, None)  # reset/restart
                return
            delta.append((b_cur, c_cur - c_prev))
        self._window[source] = delta

    def forget(self, source: str) -> None:
        self._prev.pop(source, None)
        self._window.pop(source, None)
        self._updated_at.pop(source, None)
        self._incarnation.pop(source, None)

    def staleness(self, source: str) -> Optional[float]:
        """Clock units since ``source`` was last updated; None when
        no clock was injected or the source was never seen."""
        if self.clock is None:
            return None
        at = self._updated_at.get(source)
        return None if at is None else self.clock() - at

    def window_count(self) -> float:
        return sum(d[-1][1] for d in self._window.values() if d)

    def merged(self) -> List[Tuple[float, float]]:
        """Cumulative (bound, count) deltas merged across sources —
        the fleet-wide distribution of observations that arrived
        between the last two scrapes of each source."""
        merged: Dict[float, float] = {}
        for delta in self._window.values():
            for bound, count in delta:
                merged[bound] = merged.get(bound, 0.0) + count
        return sorted(merged.items(), key=lambda kv: kv[0])

    def quantile(self, q: float) -> Optional[float]:
        return quantile_from_buckets(self.merged(), q)


def count_le(buckets: List[Tuple[float, float]],
             threshold: float) -> float:
    """Observations <= ``threshold`` in cumulative (bound, count)
    pairs: exact when the threshold sits on a bucket bound (SLO specs
    pick thresholds on DEFAULT_BUCKETS bounds for exactly this
    reason), linearly interpolated inside the containing bucket
    otherwise."""
    if not buckets:
        return 0.0
    prev_bound, prev_count = 0.0, 0.0
    for bound, count in buckets:
        if math.isinf(bound):
            return count if math.isinf(threshold) else prev_count
        if bound == threshold:
            return count
        if bound > threshold:
            if bound == prev_bound:
                return count
            frac = (threshold - prev_bound) / (bound - prev_bound)
            return prev_count + (count - prev_count) * max(
                0.0, min(1.0, frac))
        prev_bound, prev_count = bound, count
    return buckets[-1][1]


class CounterWindow:
    """Windowed deltas for one counter family across scrapes, with
    the same reset/incarnation re-basing discipline as
    HistogramWindow. ``label_filter`` narrows to matching children;
    ``total()`` sums each source's delta between its last two
    updates."""

    def __init__(self, family: str,
                 label_filter: Optional[Dict[str, str]] = None):
        self.family = family
        self.labels = dict(label_filter) if label_filter else None
        self._prev: Dict[str, float] = {}
        self._delta: Dict[str, float] = {}
        self._incarnation: Dict[str, object] = {}

    def _value(self, samples: Dict[str, float]) -> float:
        tot = 0.0
        for key, value in samples.items():
            name, labels = split_key(key)
            if name != self.family:
                continue
            if self.labels and any(labels.get(k) != v
                                   for k, v in self.labels.items()):
                continue
            tot += value
        return tot

    def update(self, source: str, samples: Dict[str, float],
               incarnation: Optional[object] = None) -> None:
        cur = self._value(samples)
        prev = self._prev.get(source)
        self._prev[source] = cur
        rebased = (incarnation is not None
                   and incarnation != self._incarnation.get(source))
        if incarnation is not None:
            self._incarnation[source] = incarnation
        if prev is None or rebased or cur < prev:
            self._delta.pop(source, None)
            return
        self._delta[source] = cur - prev

    def forget(self, source: str) -> None:
        self._prev.pop(source, None)
        self._delta.pop(source, None)
        self._incarnation.pop(source, None)

    def total(self) -> float:
        return sum(self._delta.values())


class SharedScraper:
    """One /metrics fetch per backend per tick, many consumers.

    The autoscale controller and the fleet SLO rollup both scrape
    every backend each tick; fetching twice not only doubles load,
    it hands the two consumers DIFFERENT cumulative counters for the
    "same" instant. SharedScraper memoizes one result — or one
    raised OSError — per URL, reused while
    ``clock() - fetched_at <= max_age`` (0.0 = same-instant only,
    which is exactly right in the simulator where both consumers
    tick at the same virtual time). Without an injected clock the
    scraper degrades to a counting passthrough: every call fetches.

    ``fetches`` counts underlying HTTP fetches so regression tests
    can assert the one-fetch-per-backend-per-tick contract.
    """

    def __init__(self, fetch_fn: Callable[..., Dict[str, float]]
                 = fetch_metrics,
                 clock: Optional[Callable[[], float]] = None,
                 max_age: float = 0.0):
        self.fetch_fn = fetch_fn
        self.clock = clock
        self.max_age = max_age
        self.fetches = 0
        self._cache: Dict[str, Tuple[
            float, Optional[Dict[str, float]], Optional[OSError]]] = {}

    def fetch(self, url: str) -> Dict[str, float]:
        if self.clock is not None:
            now = self.clock()
            ent = self._cache.get(url)
            if ent is not None and now - ent[0] <= self.max_age:
                if ent[2] is not None:
                    raise ent[2]
                return ent[1]
        self.fetches += 1
        try:
            result = self.fetch_fn(url)
        except OSError as exc:
            if self.clock is not None:
                self._cache[url] = (now, None, exc)
            raise
        if self.clock is not None:
            self._cache[url] = (now, result, None)
        return result

    def forget(self, url: str) -> None:
        self._cache.pop(url, None)
