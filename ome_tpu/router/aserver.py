"""Asyncio router data path.

The threaded router (server.py) carries one OS thread per in-flight
SSE stream — ~8 MiB of stack per slow reader, and a hard ceiling in
the low thousands of concurrent streams per process. This server
keeps the ENTIRE policy surface of the threaded one — breaker and
draining state machine, cache_aware + fleet prefix directory, class
headers, traceparent spans, the guarded /backends admin API,
/metrics — but proxies on a single event loop: tens of thousands of
concurrent streams are tens of thousands of coroutines, not threads.

Data-path rules (docs/router-ha.md):

  * per-stream buffers are BOUNDED (an asyncio.Queue of
    --stream-buffer chunks between the upstream reader and the
    client writer). A slow client fills its own queue, at which point
    that ONE stream's upstream read pauses (TCP backpressure to the
    engine) — it never stalls the loop or any other stream, and
    memory per stream stays bounded;
  * a client disconnect cancels the upstream fetch: the connection
    watcher sees EOF on the client socket and cancels the proxy task,
    which closes the upstream connection on its way out (the engine
    sees the close and stops generating);
  * all blocking I/O stays on threads — the health loop and the
    gossip pull loop (gossip.py) run exactly as before. The event
    loop talks to the Router/Backend/PrefixDirectory policy objects
    (reused unchanged from server.py) directly: their critical
    sections are leaf threading.Locks held for microseconds, never
    across I/O, which is the explicit thread<->event-loop boundary —
    cheap enough to take on the loop, and the only shared state.

Fault injection uses faults.afire (asyncio.sleep for slow rules): a
time.sleep here would stall every stream on the loop, not just the
faulted one — exactly what omelint's blocking-in-async rule rejects.

Multi-replica: N of these processes front the same engine pool; they
share breaker/draining observations and the prefix directory via
gossip.py anti-entropy (--gossip-peer), serving snapshots at
/gossip/state. Losing a replica loses its connections, never
correctness (journal durability lives in the engines).
"""

from __future__ import annotations

import argparse
import asyncio
import http.client
import json
import logging
import os
import random
import threading
import time
import urllib.parse
from typing import Dict, Optional, Tuple

from ..priority import DEFAULT_PRIORITY, PRIORITY_CLASSES, coerce_priority
from ..telemetry import tracing
from ..telemetry.reqlog import coerce as _coerce_reqlog
from .gossip import GossipAgent, GossipState
from .server import (Backend, RetryBudget, Router, _BackendDraining,
                     _ClientGone, _ResponseStarted, _parse_selector,
                     affinity_from_payload, discover_backends,
                     prefix_digest)

log = logging.getLogger("ome.router.async")


class _UpstreamError(Exception):
    """Retryable transport failure talking to a backend (the asyncio
    analogue of urllib.error.URLError in the threaded path)."""


class _Headers(dict):
    """Case-insensitive header view: keys are stored lowercased, and
    get() lowercases its argument — the one behavior the shared
    helpers (tracing.from_headers, priority coercion) rely on from
    http.server's message object."""

    def get(self, key, default=None):
        return dict.get(self, key.lower(), default)


async def _bounded(coro, deadline_mono: Optional[float]):
    """Await `coro` within the remaining budget of an absolute
    monotonic deadline (None = unbounded)."""
    if deadline_mono is None:
        return await coro
    remaining = deadline_mono - time.monotonic()
    if remaining <= 0:
        raise asyncio.TimeoutError("upstream deadline exceeded")
    return await asyncio.wait_for(coro, timeout=remaining)


class AsyncRouterServer:
    """Single-event-loop router front end over the threaded policy
    core. Constructor surface mirrors RouterServer, plus gossip and
    the stream-buffer bound."""

    def __init__(self, router: Router, host: str = "0.0.0.0",
                 port: int = 0, retries: int = 2,
                 retry_backoff: float = 0.05,
                 retry_budget_ratio: float = 0.2,
                 request_log=None, span_log=None,
                 debug_endpoints: bool = False,
                 gossip: Optional[GossipState] = None,
                 stream_buffer: int = 64):
        self.router = router
        self.host = host
        self.port = port
        self.retries = retries
        self.retry_backoff = retry_backoff
        self.debug_endpoints = debug_endpoints
        # fleet SLO rollup (docs/slo.md): attached by main() when
        # --slo-spec is given; GET /slo answers 404 until then
        self.slo_rollup = None
        self.gossip = gossip
        self.stream_buffer = max(1, stream_buffer)
        self.budget = RetryBudget(ratio=retry_budget_ratio)
        self._jitter = random.Random(1)
        self.request_log = _coerce_reqlog(request_log)
        self.span_log = tracing.coerce_span_log(span_log,
                                                component="router")
        self._h_request = router.registry.histogram(
            "ome_router_request_seconds",
            "End-to-end proxied request seconds (retries included)")
        _fam_class = router.registry.counter(
            "ome_router_class_requests_total",
            "Completion requests proxied, by priority class",
            labelnames=("class",))
        self._c_class = {c: _fam_class.labels(**{"class": c})
                         for c in PRIORITY_CLASSES}
        # asyncio data-path telemetry (docs/observability.md)
        self._g_open_streams = router.registry.gauge(
            "ome_router_open_streams",
            "SSE streams currently being proxied by this replica")
        self._c_backpressure = router.registry.counter(
            "ome_router_stream_backpressure_total",
            "Stream chunks that found the per-stream buffer full (the "
            "slow client is now backpressuring its upstream read)")
        self._c_disconnects = router.registry.counter(
            "ome_router_client_disconnects_total",
            "Proxied requests whose client vanished mid-flight")
        # mutated only on the event loop (single-threaded); exported
        # to the gauge at scrape time
        self._open_streams = 0
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._stopping: Optional[asyncio.Event] = None
        self._ready = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "AsyncRouterServer":
        """Run the event loop on a dedicated thread (the process main
        thread keeps the threaded ecosystem: signal handling, health
        loop, gossip agent, tests driving with urllib)."""
        self.router.start_health_loop()
        self._thread = threading.Thread(target=self._run,
                                        name="ome-arouter", daemon=True)
        self._thread.start()
        if not self._ready.wait(timeout=10):
            raise RuntimeError("async router failed to start")
        return self

    def _run(self):
        asyncio.run(self._serve())

    async def _serve(self):
        server = await asyncio.start_server(
            self._handle_conn, host=self.host, port=self.port)
        self.port = server.sockets[0].getsockname()[1]
        self._loop = asyncio.get_running_loop()
        self._stopping = asyncio.Event()
        self._ready.set()
        async with server:
            await self._stopping.wait()

    def stop(self):
        self.router.stop()
        if self._loop is not None and self._stopping is not None:
            self._loop.call_soon_threadsafe(self._stopping.set)
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.request_log.close()
        self.span_log.close()

    # -- HTTP plumbing -------------------------------------------------

    async def _read_request(self, reader: asyncio.StreamReader):
        line = await reader.readline()
        if not line or not line.strip():
            return None
        parts = line.decode("latin-1").split()
        if len(parts) < 2:
            return None
        method, target = parts[0].upper(), parts[1]
        headers = _Headers()
        while True:
            raw = await reader.readline()
            if raw in (b"\r\n", b"\n", b""):
                break
            name, _, value = raw.decode("latin-1").partition(":")
            headers[name.strip().lower()] = value.strip()
        n = int(headers.get("Content-Length") or 0)
        body = await reader.readexactly(n) if n > 0 else b""
        return method, target, headers, body

    @staticmethod
    def _head(code: int, headers) -> bytes:
        reason = http.client.responses.get(code, "Unknown")
        lines = [f"HTTP/1.1 {code} {reason}"]
        lines += [f"{k}: {v}" for k, v in headers]
        lines.append("Connection: close")
        return ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1")

    async def _send_body(self, writer, code: int, body: bytes,
                         content_type: str, extra: Optional[dict] = None):
        headers = [("Content-Type", content_type),
                   ("Content-Length", str(len(body)))]
        headers += list((extra or {}).items())
        try:
            writer.write(self._head(code, headers) + body)
            await writer.drain()
        except (OSError, ConnectionError) as e:
            raise _ClientGone(str(e)) from e

    async def _send_json(self, writer, code: int, obj,
                         extra: Optional[dict] = None):
        await self._send_body(writer, code, json.dumps(obj).encode(),
                              "application/json", extra)

    # -- connection handling -------------------------------------------

    async def _handle_conn(self, reader, writer):
        try:
            try:
                request = await asyncio.wait_for(
                    self._read_request(reader), timeout=120.0)
            except (asyncio.TimeoutError, asyncio.IncompleteReadError,
                    OSError, ValueError):
                return
            if request is None:
                return
            method, path, headers, body = request
            await self._dispatch(method, path, headers, body,
                                 reader, writer)
        except _ClientGone:
            self._c_disconnects.inc()
        except asyncio.CancelledError:
            raise
        except Exception:
            log.exception("connection handler failed")
            try:
                await self._send_json(writer, 500,
                                      {"error": "internal error"})
            except Exception:
                pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    def _guard(self) -> bool:
        return self.debug_endpoints

    async def _dispatch(self, method, path, headers, body,
                        reader, writer):
        router = self.router
        if method == "GET":
            if path in ("/health", "/healthz"):
                snap = router.backend_snapshot()
                up = any(b["healthy"] for b in snap)
                return await self._send_json(
                    writer, 200 if up else 503, {
                        "status": "ok" if up else "no healthy backends",
                        "backends": [
                            {k: b[k] for k in
                             ("url", "pool", "healthy", "draining")}
                            for b in snap]})
            if path == "/gossip/state":
                # the anti-entropy protocol surface: unguarded like
                # /health — it carries observations, not admin power
                if self.gossip is None:
                    return await self._send_json(
                        writer, 404, {"error": "gossip disabled"})
                return await self._send_json(writer, 200,
                                             self.gossip.snapshot())
            if path == "/backends":
                if not self._guard():
                    return await self._send_json(writer, 403, {
                        "error": "debug endpoints disabled "
                                 "(enable --debug-endpoints)"})
                return await self._send_json(writer, 200, {
                    "backends": router.backend_snapshot()})
            if path == "/slo":
                # fleet SLO attainment / budget / alert state
                # (docs/slo.md), guarded like /backends
                if not self._guard():
                    return await self._send_json(writer, 403, {
                        "error": "debug endpoints disabled "
                                 "(enable --debug-endpoints)"})
                if self.slo_rollup is None:
                    return await self._send_json(writer, 404, {
                        "error": "slo rollup not configured "
                                 "(start with --slo-spec)"})
                return await self._send_json(
                    writer, 200, self.slo_rollup.report())
            if path == "/debug/state":
                if not self._guard():
                    return await self._send_json(writer, 403, {
                        "error": "debug endpoints disabled "
                                 "(enable --debug-endpoints)"})
                return await self._send_json(writer, 200, {
                    "backends": router.backend_snapshot(),
                    "gossip": (self.gossip.stats()
                               if self.gossip else None),
                    "streams": {
                        "open": self._open_streams,
                        "backpressure_total":
                            self._c_backpressure.value,
                        "client_disconnects_total":
                            self._c_disconnects.value}})
            if path == "/metrics":
                router.update_gauges()
                self._g_open_streams.set(self._open_streams)
                body_b = router.registry.render().encode()
                return await self._send_body(
                    writer, 200, body_b, "text/plain; version=0.0.4")
            return await self._proxy(method, path, headers, b"",
                                     False, "", reader, writer)
        if method == "POST":
            if path == "/backends":
                return await self._backends_mutate(writer, body,
                                                   add=True)
            try:
                payload = json.loads(body or b"{}")
            except ValueError:
                payload = {}
            cls = None
            if path in ("/v1/completions", "/v1/chat/completions"):
                try:
                    cls = coerce_priority(
                        headers.get("X-OME-Priority")
                        or payload.get("priority"))
                except ValueError:
                    cls = DEFAULT_PRIORITY
                self._c_class[cls].inc()
            stream = bool(payload.get("stream"))
            mdl = payload.get("model")
            return await self._proxy(
                method, path, headers, body, stream,
                affinity_from_payload(payload), reader, writer,
                cls=cls,
                model=mdl if isinstance(mdl, str) else None)
        if method == "DELETE":
            if path == "/backends":
                return await self._backends_mutate(writer, body,
                                                   add=False)
            return await self._send_json(writer, 404,
                                         {"error": "not found"})
        return await self._send_json(writer, 405,
                                     {"error": "method not allowed"})

    async def _backends_mutate(self, writer, body: bytes, add: bool):
        if not self._guard():
            return await self._send_json(writer, 403, {
                "error": "debug endpoints disabled "
                         "(enable --debug-endpoints)"})
        try:
            payload = json.loads(body or b"{}")
        except ValueError:
            payload = {}
        url = payload.get("url")
        if not url:
            return await self._send_json(writer, 400,
                                         {"error": "missing 'url'"})
        if add:
            b = self.router.add_backend(url,
                                        payload.get("pool") or "engine")
            return await self._send_json(writer, 200, {
                "ok": True, "url": b.url, "pool": b.pool})
        removed = self.router.remove_backend(url)
        return await self._send_json(writer, 200 if removed else 404, {
            "ok": removed, "url": url.rstrip("/")})

    # -- proxy path ----------------------------------------------------

    def _pick_pool(self, headers) -> str:
        want = headers.get("X-OME-Pool") or "engine"
        if self.router._alive(want):
            return want
        other = "decoder" if want == "engine" else "engine"
        return other if self.router._alive(other) else want

    @staticmethod
    def _deadline(headers) -> Optional[float]:
        hdr = headers.get("X-Request-Deadline")
        if not hdr:
            return None
        try:
            return float(hdr)
        except ValueError:
            return None

    async def _proxy(self, method, path, headers, body, stream,
                     affinity, reader, writer, cls=None, model=None):
        ctx = tracing.from_headers(headers)
        t0 = time.monotonic()
        outcome = {"backend": None, "pool": None,
                   "status": "error", "retries": 0,
                   "class": cls}
        span = None
        if self.span_log.enabled:
            span = tracing.Span("router.request",
                                trace_id=ctx.trace_id,
                                span_id=ctx.span_id, start_mono=t0)
            span.set(path=path)
        # disconnect watcher: once the request body is consumed, any
        # read on the client socket resolves only at EOF — the client
        # hanging up. Cancelling the proxy task tears the upstream
        # connection down with it (the fetch is cancelled, the engine
        # stops generating for a viewer that left).
        gone = {"flag": False}
        me = asyncio.current_task()
        async def watch():
            try:
                while True:
                    data = await reader.read(65536)
                    if not data:
                        break
            except (OSError, asyncio.CancelledError):
                return
            gone["flag"] = True
            me.cancel()
        watcher = asyncio.create_task(watch())
        try:
            return await self._route(method, path, headers, body,
                                     stream, affinity, ctx, outcome,
                                     writer, model=model)
        except asyncio.CancelledError:
            if not gone["flag"]:
                raise
            # real SSE clients hang up the moment they read the
            # `data: [DONE]` sentinel — the watcher's cancellation
            # then races the relay's own return. If the full
            # response was already delivered the request was SERVED;
            # only a mid-response hangup is a true client_gone
            # (docs/slo.md availability classification)
            outcome["status"] = ("ok" if outcome.get("delivered")
                                 else "client_gone")
            raise _ClientGone("client closed connection") from None
        finally:
            watcher.cancel()
            dur = time.monotonic() - t0
            self._h_request.observe(dur)
            if cls is not None and outcome["status"] != "client_gone":
                # availability: everything the router answered is
                # good except its own failure statuses
                self.router.note_outcome(
                    cls, outcome["status"] == "ok")
            if span is not None:
                span.set(pool=outcome["pool"],
                         backend=outcome["backend"],
                         status=outcome["status"],
                         retries=outcome["retries"])
                span.end(t0 + dur)
                self.span_log.write(span)
            if self.request_log.enabled:
                self.request_log.write({
                    "component": "router",
                    "trace_id": ctx.trace_id,
                    "span_id": ctx.span_id,
                    "path": path,
                    "pool": outcome["pool"],
                    "backend": outcome["backend"],
                    "status": outcome["status"],
                    "retries": outcome["retries"],
                    "duration_s": round(dur, 6)})

    async def _route(self, method, path, headers, body, stream,
                     affinity, ctx, outcome, writer, model=None):
        router = self.router
        router.inc("requests_total")
        self.budget.deposit()
        deadline = self._deadline(headers)
        # model-aware gate (docs/model-fleet.md) — same verdicts as
        # the threaded router: 404 unknown, 503 + Retry-After cold,
        # steer when serving, legacy any-backend when routing is off
        if model:
            verdict, _ = router.classify_model(model)
            if verdict == "unknown":
                router.note_model_unknown()
                outcome["status"] = "unknown_model"
                return await self._send_json(writer, 404, {
                    "error": f"model {model!r} is not served "
                             "by this fleet",
                    "model": model})
            if verdict == "cold":
                ra = router.model_map.retry_after(model)
                router.note_model_cold(model)
                if self.span_log.enabled:
                    cspan = tracing.Span(
                        "router.cold_start",
                        trace_id=ctx.trace_id,
                        parent_id=ctx.span_id)
                    cspan.set(model=model, retry_after=ra)
                    self.span_log.write(cspan)
                outcome["status"] = "cold_start"
                return await self._send_json(writer, 503, {
                    "error": f"model {model!r} is cold "
                             "(no live backend yet)",
                    "model": model, "retry_after": ra},
                    extra={"Retry-After": str(ra)})
            if verdict == "serving":
                router.note_model_request(model)
            else:
                model = None  # routing off for this name
        pool = self._pick_pool(headers)
        outcome["pool"] = pool
        peer_hint = None
        if affinity and router.policy == "cache_aware":
            peer_hint = router.prefix_directory.lookup(
                prefix_digest(affinity))
            if peer_hint is not None:
                router.inc("prefix_directory_hits_total")
        tried: set = set()
        last_err = "no healthy backends"
        failures = 0
        need_backoff = False
        while failures <= self.retries:
            if deadline is not None and time.time() >= deadline:
                router.inc("deadline_shed_total")
                outcome["status"] = "deadline"
                return await self._send_json(writer, 504, {
                    "error": "request deadline exceeded"})
            if need_backoff:
                need_backoff = False
                if not self.budget.withdraw():
                    router.inc("retry_budget_exhausted_total")
                    break
                delay = (self.retry_backoff * (2 ** (failures - 1))
                         * (1 + self._jitter.random()))
                await asyncio.sleep(delay)
            backend = router.pick(pool, affinity, exclude=tried,
                                  model=model)
            if backend is None:
                break
            tried.add(backend.url)
            outcome["backend"] = backend.url
            outcome["retries"] = failures
            child = ctx.child()
            aspan = None
            if self.span_log.enabled:
                aspan = tracing.Span("router.attempt",
                                     trace_id=ctx.trace_id,
                                     parent_id=ctx.span_id,
                                     span_id=child.span_id)
                aspan.set(backend=backend.url, retries=failures)
            try:
                result = await self._forward(
                    backend, method, path, headers, body, stream,
                    deadline, trace=child,
                    prefix_peer=(peer_hint
                                 if peer_hint != backend.url
                                 else None),
                    writer=writer, outcome=outcome)
                router.note_result(backend, ok=True)
                outcome["status"] = "ok"
                if aspan is not None:
                    self.span_log.write(aspan.set(status="ok"))
                return result
            except _BackendDraining:
                router.note_draining(backend)
                router.inc("draining_skips_total")
                log.info("backend %s draining; redirecting",
                         backend.url)
                if aspan is not None:
                    self.span_log.write(aspan.set(status="draining"))
                continue
            except _ClientGone:
                router.probe_aborted(backend)
                outcome["status"] = "client_gone"
                if aspan is not None:
                    self.span_log.write(
                        aspan.set(status="client_gone"))
                raise
            except asyncio.CancelledError:
                # the disconnect watcher (or shutdown) cancelled us
                # mid-forward: release any half-open probe slot —
                # same discipline as _ClientGone
                router.probe_aborted(backend)
                raise
            except _ResponseStarted as e:
                router.note_result(backend, ok=False)
                log.warning("backend %s died mid-response: %s",
                            backend.url, e)
                try:
                    writer.write(b"0\r\n\r\n")
                except (OSError, ConnectionError):
                    pass
                outcome["status"] = "stream_abort"
                if aspan is not None:
                    self.span_log.write(
                        aspan.set(status="stream_abort"))
                return None
            except _UpstreamError as e:
                last_err = str(e)
                router.note_result(backend, ok=False)
                router.inc("retries_total")
                log.warning("backend %s failed (%s); retrying",
                            backend.url, e)
                if aspan is not None:
                    self.span_log.write(aspan.set(
                        status="error", error=str(e)))
                failures += 1
                need_backoff = True
        router.inc("no_backend_total")
        outcome["status"] = "no_backend"
        await self._send_json(writer, 503, {
            "error": f"routing failed: {last_err}"},
            extra={"Retry-After": "1"})

    # -- upstream client -----------------------------------------------

    async def _open_upstream(self, url: str, method: str, path: str,
                             headers: Dict[str, str], body: bytes,
                             deadline_mono: float
                             ) -> Tuple[asyncio.StreamReader,
                                        asyncio.StreamWriter]:
        parts = urllib.parse.urlsplit(url)
        port = parts.port or (443 if parts.scheme == "https" else 80)
        reader, writer = await _bounded(
            asyncio.open_connection(
                parts.hostname, port,
                ssl=True if parts.scheme == "https" else None),
            deadline_mono)
        lines = [f"{method} {path} HTTP/1.1",
                 f"Host: {parts.netloc}",
                 "Connection: close",
                 f"Content-Length: {len(body)}"]
        lines += [f"{k}: {v}" for k, v in headers.items()]
        writer.write(("\r\n".join(lines) + "\r\n\r\n").encode("latin-1")
                     + body)
        await _bounded(writer.drain(), deadline_mono)
        return reader, writer

    @staticmethod
    async def _read_head(reader, deadline_mono
                         ) -> Tuple[int, _Headers]:
        status_line = await _bounded(reader.readline(), deadline_mono)
        parts = status_line.decode("latin-1").split(None, 2)
        if len(parts) < 2 or not parts[1].isdigit():
            raise _UpstreamError(
                f"malformed status line {status_line!r}")
        status = int(parts[1])
        headers = _Headers()
        while True:
            raw = await _bounded(reader.readline(), deadline_mono)
            if raw in (b"\r\n", b"\n", b""):
                break
            name, _, value = raw.decode("latin-1").partition(":")
            headers[name.strip().lower()] = value.strip()
        return status, headers

    @staticmethod
    async def _iter_chunks(reader, deadline_mono):
        """Decode Transfer-Encoding: chunked frames (the engine's SSE
        framing) into raw byte chunks."""
        while True:
            size_line = await _bounded(reader.readline(), deadline_mono)
            if not size_line:
                return  # upstream closed at a frame boundary
            size = int(size_line.split(b";")[0].strip() or b"0", 16)
            if size == 0:
                await _bounded(reader.readline(), deadline_mono)
                return
            data = await _bounded(reader.readexactly(size),
                                  deadline_mono)
            await _bounded(reader.readexactly(2), deadline_mono)
            yield data

    async def _read_body(self, reader, rheaders, deadline_mono) -> bytes:
        te = (rheaders.get("Transfer-Encoding") or "").lower()
        if "chunked" in te:
            out = []
            async for data in self._iter_chunks(reader, deadline_mono):
                out.append(data)
            return b"".join(out)
        n = rheaders.get("Content-Length")
        if n is not None:
            return await _bounded(reader.readexactly(int(n)),
                                  deadline_mono)
        return await _bounded(reader.read(-1), deadline_mono)

    async def _forward(self, backend: Backend, method, path, headers,
                       body, stream, deadline, trace, prefix_peer,
                       writer, outcome=None):
        from .. import faults

        await faults.afire("router_forward", key=backend.url,
                           exc=_UpstreamError)
        fwd = {"Content-Type": "application/json"}
        if trace is not None:
            fwd[tracing.TRACEPARENT_HEADER] = trace.header()
        pri = headers.get("X-OME-Priority")
        if pri:
            fwd["X-OME-Priority"] = pri
        if prefix_peer:
            fwd["X-OME-Prefix-Peer"] = prefix_peer
            self.router.inc("prefix_directory_peer_fetches_total")
        timeout = 600.0
        if deadline is not None:
            fwd["X-Request-Deadline"] = repr(deadline)
            timeout = max(min(timeout, deadline - time.time()), 0.05)
        deadline_mono = time.monotonic() + timeout
        self.router.adjust_inflight(backend, 1)
        up_writer = None
        try:
            try:
                up_reader, up_writer = await self._open_upstream(
                    backend.url, method, path, fwd, body,
                    deadline_mono)
                status, rheaders = await self._read_head(
                    up_reader, deadline_mono)
            except (OSError, ConnectionError, asyncio.TimeoutError,
                    asyncio.IncompleteReadError, ValueError) as e:
                raise _UpstreamError(str(e)) from e
            if status == 503 and rheaders.get("X-OME-Draining"):
                raise _BackendDraining(backend.url)
            if status >= 500:
                raise _UpstreamError(f"backend returned {status}")
            if status >= 400:
                # application response: relay verbatim, no failover
                try:
                    data = await self._read_body(up_reader, rheaders,
                                                 deadline_mono)
                except (OSError, asyncio.TimeoutError,
                        asyncio.IncompleteReadError, ValueError) as e:
                    raise _UpstreamError(str(e)) from e
                extra = {}
                ra = rheaders.get("Retry-After")
                if ra:
                    extra["Retry-After"] = ra
                await self._send_body(
                    writer, status, data,
                    rheaders.get("Content-Type", "application/json"),
                    extra)
                if outcome is not None:
                    outcome["delivered"] = True
                return None
            if stream:
                await self._relay_stream(up_reader, rheaders, status,
                                         writer, deadline_mono,
                                         outcome=outcome)
                return None
            try:
                data = await self._read_body(up_reader, rheaders,
                                             deadline_mono)
            except (OSError, asyncio.TimeoutError,
                    asyncio.IncompleteReadError, ValueError) as e:
                # nothing reached the client yet: retryable
                raise _UpstreamError(str(e)) from e
            await self._send_body(
                writer, status, data,
                rheaders.get("Content-Type", "application/json"))
            if outcome is not None:
                outcome["delivered"] = True
            return None
        finally:
            self.router.adjust_inflight(backend, -1)
            if up_writer is not None:
                # every exit path — success, retryable error, client
                # disconnect cancellation — closes the upstream
                # connection, which is what cancels the fetch
                up_writer.close()

    async def _relay_stream(self, up_reader, rheaders, status, writer,
                            deadline_mono, outcome=None):
        """Backpressure-aware SSE relay: upstream chunks flow through
        a BOUNDED queue into the client socket. The pump (upstream
        reader) and the writer are separate coroutines, so a slow
        client never blocks the pump until its own buffer fills —
        then that one stream's upstream read pauses (TCP backpressure
        to the engine) while every other stream keeps flowing."""
        try:
            writer.write(self._head(status, [
                ("Content-Type", rheaders.get("Content-Type",
                                              "text/event-stream")),
                ("Transfer-Encoding", "chunked")]))
            await writer.drain()
        except (OSError, ConnectionError) as e:
            raise _ClientGone(str(e)) from e
        q: asyncio.Queue = asyncio.Queue(maxsize=self.stream_buffer)
        chunked = "chunked" in (rheaders.get("Transfer-Encoding")
                                or "").lower()

        async def pump():
            try:
                if chunked:
                    async for data in self._iter_chunks(up_reader,
                                                        deadline_mono):
                        if q.full():
                            self._c_backpressure.inc()
                        await q.put(("data", data))
                else:
                    while True:
                        data = await _bounded(up_reader.read(65536),
                                              deadline_mono)
                        if not data:
                            break
                        if q.full():
                            self._c_backpressure.inc()
                        await q.put(("data", data))
                await q.put(("eof", None))
            except asyncio.CancelledError:
                raise
            except Exception as e:
                await q.put(("err", e))

        pump_task = asyncio.create_task(pump())
        self._open_streams += 1
        try:
            # real SSE clients hang up the moment they read the
            # `data: [DONE]` sentinel, without draining the trailing
            # blank line or the chunked terminator — once the
            # sentinel is delivered the request was SERVED, and
            # classifying it client_gone would poison the
            # availability SLO (docs/slo.md)
            done_sent = False
            while True:
                kind, payload = await q.get()
                if kind == "eof":
                    break
                if kind == "err":
                    raise _ResponseStarted(str(payload))
                try:
                    writer.write(f"{len(payload):x}\r\n".encode()
                                 + payload + b"\r\n")
                    await writer.drain()
                except (OSError, ConnectionError) as e:
                    if done_sent:
                        break
                    raise _ClientGone(str(e)) from e
                if b"data: [DONE]" in payload:
                    done_sent = True
                    if outcome is not None:
                        # the disconnect watcher may cancel us the
                        # instant the client reads the sentinel —
                        # record that the response is complete so
                        # that cancellation classifies as served
                        outcome["delivered"] = True
            try:
                writer.write(b"0\r\n\r\n")
                await writer.drain()
            except (OSError, ConnectionError):
                # upstream is drained and every body byte was
                # relayed: a client that hangs up between the last
                # event and the terminating chunk still received the
                # whole response — served, not abandoned
                pass
            if outcome is not None:
                outcome["delivered"] = True
        finally:
            self._open_streams -= 1
            pump_task.cancel()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ome-arouter")
    p.add_argument("--backend", action="append", default=[],
                   help="engine URL (repeatable); pool prefix with "
                        "'decoder=' routes to the decode pool")
    p.add_argument("--policy", default="cache_aware",
                   choices=("cache_aware", "round_robin", "random"))
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--bind", default="0.0.0.0")
    p.add_argument("--health-interval", type=float, default=10.0)
    p.add_argument("--retries", type=int, default=2)
    p.add_argument("--retry-backoff", type=float, default=0.05)
    p.add_argument("--cb-threshold", type=int, default=3)
    p.add_argument("--cb-cooldown", type=float, default=1.0)
    p.add_argument("--faults", default=None,
                   help="deterministic fault-injection spec "
                        "(ome_tpu/faults.py grammar); also via "
                        "OME_FAULTS")
    p.add_argument("--debug-endpoints", action="store_true")
    p.add_argument("--model-catalog", default=None,
                   help="model catalog JSON ({model: {warmup_ms, "
                        "weight_bytes}}): declares the fleet's model "
                        "set and turns on model-aware enforcement — "
                        "unknown model 404, known-but-cold 503 + "
                        "Retry-After (docs/model-fleet.md)")
    p.add_argument("--slo-spec", default=None,
                   help="SLO spec JSON (config/slo.json format): "
                        "starts the fleet rollup loop and serves "
                        "GET /slo + ome_slo_* metrics (docs/slo.md)")
    p.add_argument("--slo-interval", type=float, default=5.0,
                   help="seconds between fleet SLO rollup scrapes")
    p.add_argument("--request-log", default=None)
    p.add_argument("--span-log", default=None)
    p.add_argument("--stream-buffer", type=int, default=64,
                   help="per-stream chunk buffer between the upstream "
                        "reader and the client writer (bounds memory; "
                        "a full buffer backpressures that stream's "
                        "upstream read)")
    p.add_argument("--gossip-peer", action="append", default=[],
                   help="peer router base URL to pull /gossip/state "
                        "from (repeatable); enables the anti-entropy "
                        "agent on the health-loop cadence")
    p.add_argument("--replica-id", default=None,
                   help="stable replica identity for gossip LWW "
                        "tie-breaks (default: host:port:pid)")
    p.add_argument("--engine-selector", default=None)
    p.add_argument("--decoder-selector", default=None)
    p.add_argument("--namespace", default="default")
    p.add_argument("--kubeconfig", default=None)
    p.add_argument("--kube-server", default=None)
    p.add_argument("--in-cluster", action="store_true")
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    if args.faults:
        from .. import faults
        faults.install(args.faults)
        log.warning("fault injection ACTIVE: %s", args.faults)
    backends = []
    for spec in args.backend:
        if spec.startswith("decoder="):
            backends.append(Backend(spec[len("decoder="):], "decoder"))
        elif spec.startswith("engine="):
            backends.append(Backend(spec[len("engine="):], "engine"))
        else:
            backends.append(Backend(spec, "engine"))
    if args.engine_selector or args.decoder_selector:
        from ..cmd.manager import build_client
        client = build_client(args)
        if args.engine_selector:
            backends += discover_backends(
                client, args.namespace,
                _parse_selector(args.engine_selector), "engine")
        if args.decoder_selector:
            backends += discover_backends(
                client, args.namespace,
                _parse_selector(args.decoder_selector), "decoder")
        log.info("discovered %d backends via selectors", len(backends))
    if not backends:
        p.error("at least one --backend or --engine-selector is "
                "required")
    router = Router(backends, policy=args.policy,
                    health_interval=args.health_interval,
                    cb_threshold=args.cb_threshold,
                    cb_cooldown=args.cb_cooldown)
    if args.model_catalog:
        with open(args.model_catalog, "r", encoding="utf-8") as f:
            router.model_map.load_catalog(json.load(f))
        log.info("model catalog loaded: %s (enforcement on)",
                 args.model_catalog)
    router.check_health_once()
    replica_id = args.replica_id or \
        f"{args.bind}:{args.port}:{os.getpid()}"
    gossip = GossipState(router, replica_id)
    srv = AsyncRouterServer(
        router, host=args.bind, port=args.port, retries=args.retries,
        retry_backoff=args.retry_backoff,
        request_log=args.request_log, span_log=args.span_log,
        debug_endpoints=args.debug_endpoints, gossip=gossip,
        stream_buffer=args.stream_buffer).start()
    agent = None
    if args.gossip_peer:
        agent = GossipAgent(gossip, args.gossip_peer,
                            interval=args.health_interval).start()
    if args.slo_spec:
        from ..autoscale.scrape import SharedScraper
        from ..slo import FleetRollup
        from ..slo import load as load_slo
        from ..slo.rollup import start_thread as start_slo_thread
        scraper = SharedScraper(clock=time.monotonic,
                                max_age=args.slo_interval / 2.0)
        srv.slo_rollup = FleetRollup(
            load_slo(args.slo_spec), clock=time.monotonic,
            fetch_fn=scraper.fetch,
            backends_fn=router.backend_snapshot,
            registry=router.registry,
            local_samples_fn=router.registry.snapshot)
        start_slo_thread(srv.slo_rollup, args.slo_interval)
        log.info("slo rollup active: %s every %.1fs",
                 args.slo_spec, args.slo_interval)
    log.info("async router on :%d over %d backends (policy=%s, "
             "replica=%s, peers=%d)", srv.port, len(backends),
             args.policy, replica_id, len(args.gossip_peer))
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        if agent is not None:
            agent.stop()
        srv.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
