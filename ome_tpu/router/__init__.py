"""ome-router: OpenAI-API load balancer / PD request router.

The binary behind the catalog's RouterConfig (the reference deploys
sglang-router for this role — deepseek-rdma-pd-rt.yaml:490-515 runs it
with worker service-discovery selectors and `--policy`). Routes
OpenAI-surface requests across engine replicas with cache-aware
(prefix-affinity), round-robin, or random policies, health-checks its
backends, and fails over on errors.
"""

from .server import RouterServer, main  # noqa: F401
