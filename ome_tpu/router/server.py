"""Router implementation.

Design (vs the reference's sglang-router, which it deploys as the
router component — SURVEY.md §2.9 "PD disaggregation"):

  * backends come from static --backend flags or from watching
    Endpoints-like service discovery through the shared client
    (component selectors, the same contract RouterConfig carries in
    the catalog: engine-selector / decoder-selector);
  * policies: `cache_aware` (consistent prefix-hash affinity, so a
    conversation keeps hitting the replica whose KV cache already
    holds its prefix), `round_robin`, `random`;
  * health: background probing of each backend's /health; unhealthy
    backends leave the rotation, failed requests retry on the next
    backend;
  * resilience (docs/failure-semantics.md): a per-backend CIRCUIT
    BREAKER layered on the health loop — `cb_threshold` consecutive
    request failures open the circuit for an exponentially growing
    cooldown, after which ONE half-open probe request re-admits (or
    re-opens) it. The health probe alone cannot do this: a backend
    whose /health lies (or flaps) would otherwise re-enter rotation
    every probe interval and fail live traffic each time. Retries
    draw from a token-bucket RETRY BUDGET (a fixed fraction of
    request volume) with exponential backoff + jitter, so a dying
    pool degrades into fast 503s instead of a retry storm;
  * deadlines: the X-Request-Deadline header (absolute epoch seconds)
    propagates to backends, bounds the upstream timeout, and expired
    requests fail fast with 504 instead of burning a retry;
  * streaming passthrough: SSE bodies relay chunk-by-chunk.

PD note: the KV handoff itself lives in the engines — decode nodes
pull the prefix KV from the prefill pool over /pd/prefill
(engine/pd.py wire format + RemotePrefillEngine); the router's PD job
is steering — completions go to the DECODE pool (whose engines fetch
prefill remotely), and cache-aware affinity keeps same-prefix traffic
on the same prefill node so its radix prefix cache can hit.
"""

from __future__ import annotations

import argparse
import hashlib
import itertools
import json
import logging
import random
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from ..priority import DEFAULT_PRIORITY, PRIORITY_CLASSES, coerce_priority
from ..telemetry import Registry, tracing
from ..telemetry.reqlog import coerce as _coerce_reqlog

log = logging.getLogger("ome.router")

_COUNTER_HELP = {
    "requests_total": "Requests received by the router",
    "retries_total": "Backend failures that triggered a failover",
    "no_backend_total": "Requests that exhausted every backend (503)",
    "circuit_open_total": "Circuit-breaker open transitions",
    "retry_budget_exhausted_total":
        "Retries suppressed by the token-bucket budget",
    "deadline_shed_total":
        "Requests shed because their deadline had passed (504)",
    "draining_skips_total":
        "Forwards redirected because the backend announced it was "
        "draining (free failover: no breaker hit, no retry token)",
    "prefix_directory_hits_total":
        "Forwarded requests whose prefix digest the fleet prefix "
        "directory mapped to a replica",
    "prefix_directory_peer_fetches_total":
        "Forwards carrying an X-OME-Prefix-Peer header because the "
        "prefix owner differed from the chosen backend (the backend "
        "fetches the prefix KV from the peer)",
}

_CB_STATE_VALUE = {"closed": 0, "half_open": 1, "open": 2}


class _ClientGone(Exception):
    """The requesting client disconnected; abort without failover."""


class _ResponseStarted(Exception):
    """Backend failed after response bytes reached the client —
    failover would corrupt the stream."""


class _BackendDraining(Exception):
    """Backend answered 503 + X-OME-Draining: it is shutting down
    gracefully. Fail over for free — the backend is HEALTHY, so the
    redirect must not trip its breaker or spend a retry token."""


class Backend:
    def __init__(self, url: str, pool: str = "engine",
                 cb_threshold: int = 3, cb_cooldown: float = 1.0,
                 cb_max_cooldown: float = 30.0):
        self.url = url.rstrip("/")
        self.pool = pool
        self.healthy = True
        self.inflight = 0
        self.last_checked = 0.0
        # circuit breaker (closed -> open -> half_open -> closed):
        # consecutive REQUEST failures trip it; the health probe does
        # not reset it — only a successful half-open data-path probe
        # closes it again (a flapping /health cannot re-admit a
        # backend that keeps failing live traffic)
        self.cb_threshold = cb_threshold
        self.cb_cooldown = cb_cooldown
        self.cb_max_cooldown = cb_max_cooldown
        self.cb_state = "closed"
        self.cb_open_until = 0.0
        self.fails = 0       # consecutive request failures
        self.cb_trips = 0    # times opened (drives the backoff)
        self._probe_inflight = False
        # half-open probe idempotency: every admitted probe carries a
        # token (minted by begin_probe); a failure verdict charges the
        # breaker AT MOST ONCE per token. Two routers probing the same
        # recovering backend concurrently (multi-replica ingress, or a
        # gossip merge releasing _probe_inflight mid-probe) would
        # otherwise double-charge cb_trips and double the cooldown
        # twice for one real failure.
        self._probe_token = 0     # last token minted
        self._probe_charged = 0   # highest token already charged
        # drain-aware routing: a draining backend (SIGTERM, finishing
        # in-flight work) leaves rotation WITHOUT being a failure —
        # distinct from the breaker's `open` (which punishes) and
        # from healthy=False (which marks it unreachable). Set by the
        # /ready probe and by X-OME-Draining responses; cleared when
        # the probe sees it ready again (rollback / cancelled drain).
        self.draining = False
        # breaker state is self-guarded: Backend now has three owners
        # (Router, pd.PrefillPool, peering.PrefixPeerClient), each
        # serializing under its OWN lock, so the state transitions
        # take this leaf lock rather than trusting any one of them.
        # Callers still hold their owner lock around selection so a
        # pick and its result-note stay paired.
        self._lock = threading.Lock()

    def record_success(self):
        with self._lock:
            self.fails = 0
            self.cb_trips = 0
            self.cb_state = "closed"
            self._probe_inflight = False
            self.healthy = True

    def begin_probe(self) -> int:
        """Admit ONE half-open probe and mint its idempotency token.
        The caller passes the token back to record_failure so a
        duplicate verdict for the same probe is a no-op."""
        with self._lock:
            self._probe_inflight = True
            self._probe_token += 1
            return self._probe_token

    def record_failure(self, now: float,
                       probe_token: Optional[int] = None):
        with self._lock:
            half_open = self.cb_state == "half_open"
            if half_open:
                # idempotency gate: a probe verdict without a token
                # adopts the latest minted one (legacy callers), and a
                # token at or below the charged high-water mark has
                # already been counted — release the slot and return.
                tok = probe_token if probe_token is not None \
                    else self._probe_token
                if tok and tok <= self._probe_charged:
                    self._probe_inflight = False
                    return
                self._probe_charged = max(self._probe_charged, tok)
            self.fails += 1
            self._probe_inflight = False
            if half_open or self.fails >= self.cb_threshold:
                self.cb_trips += 1
                self.cb_state = "open"
                self.cb_open_until = now + min(
                    self.cb_cooldown * (2 ** (self.cb_trips - 1)),
                    self.cb_max_cooldown)

    def selectable(self, now: float) -> bool:
        with self._lock:
            if self.draining:
                return False  # leaving rotation, but NOT a failure
            if self.cb_state == "open":
                if now < self.cb_open_until:
                    return False
                # cooldown over: allow probes
                self.cb_state = "half_open"
            if self.cb_state == "half_open":
                # ONE probe request at a time re-tests the backend
                return not self._probe_inflight
            return self.healthy

    def __repr__(self):
        return f"Backend({self.url}, {self.pool}, " \
               f"{'up' if self.healthy else 'down'}, " \
               f"cb={self.cb_state}" \
               f"{', draining' if self.draining else ''})"


def probe_backend_info(url: str, timeout: float = 5.0):
    """Probe /ready (falling back to /health for pre-readiness
    backends). Returns (healthy, draining, info): a draining replica
    answers /ready with 503 + {"draining": true} while still
    finishing in-flight work — it is HEALTHY but must leave the
    rotation, and re-enters it if a later probe sees 200 again.

    `info` is the parsed /ready JSON body (None when unavailable) —
    the piggyback channel for the fleet prefix directory: replicas
    report the digests of prefixes they recently served
    ("prefix_digests") on the probe the router already makes."""
    url = url.rstrip("/")
    try:
        with urllib.request.urlopen(url + "/ready",
                                    timeout=timeout) as resp:
            ok = resp.status == 200
            try:
                info = json.loads(resp.read() or b"{}")
            except ValueError:
                info = None
            return ok, False, info if isinstance(info, dict) else None
    except urllib.error.HTTPError as e:
        if e.code == 503:
            try:
                info = json.loads(e.read() or b"{}")
            except ValueError:
                info = {}
            e.close()
            if info.get("draining"):
                return True, True, info
            return False, False, None  # not ready for another reason
        e.close()
        if e.code == 404:
            # old backend without /ready: fall back to /health
            try:
                with urllib.request.urlopen(url + "/health",
                                            timeout=timeout) as resp:
                    return resp.status == 200, False, None
            except Exception:
                return False, False, None
        return False, False, None
    except Exception:
        return False, False, None


def probe_backend(url: str, timeout: float = 5.0):
    """(healthy, draining) view of probe_backend_info — the contract
    shared by the router's health loop and the PD decode node's
    prefill pool (engine/pd.py), so every pool in the system applies
    one draining/readiness discipline."""
    healthy, draining, _ = probe_backend_info(url, timeout=timeout)
    return healthy, draining


def prefix_digest(affinity_key: str) -> str:
    """Stable short digest of a request's prefix-affinity key — the
    fleet prefix directory's key. Computed identically by the router
    (from affinity_from_payload) and by replicas reporting the
    prefixes they served, so the two sides meet without shipping raw
    prompt text through health probes."""
    return hashlib.blake2b(affinity_key.encode(),
                           digest_size=8).hexdigest()


class PrefixDirectory:
    """Which replica owns which prefix digest — the fleet-scale half
    of cache-aware routing (docs/kv-hierarchy.md). Entries arrive as
    health-probe piggyback (each replica's /ready body lists the
    digests it recently served) and are looked up per forward: when
    the rendezvous-chosen backend differs from the digest's owner,
    the forward carries X-OME-Prefix-Peer so the backend can fetch
    the hot prefix KV from the owner instead of recomputing it.

    LRU-bounded; last reporter wins a digest (the directory tracks
    recency, not truth — a stale entry costs one failed peer fetch
    that falls back to local recompute)."""

    def __init__(self, max_entries: int = 4096):
        self.max_entries = max_entries
        import collections
        self._owners: "collections.OrderedDict[str, str]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()

    def update(self, url: str, digests) -> None:
        url = url.rstrip("/")
        if not isinstance(digests, (list, tuple)):
            return
        with self._lock:
            for d in digests:
                if not isinstance(d, str) or not d:
                    continue
                self._owners.pop(d, None)
                self._owners[d] = url
            while len(self._owners) > self.max_entries:
                self._owners.popitem(last=False)

    def forget(self, url: str) -> None:
        """Drop every digest owned by a removed backend."""
        url = url.rstrip("/")
        with self._lock:
            for d in [d for d, u in self._owners.items() if u == url]:
                del self._owners[d]

    def lookup(self, digest: str) -> Optional[str]:
        with self._lock:
            return self._owners.get(digest)

    def export(self) -> List[tuple]:
        """(digest, owner) pairs in LRU order (oldest first) — the
        gossip snapshot's view of the directory. Re-importing via
        update() in this order reproduces the same LRU recency."""
        with self._lock:
            return list(self._owners.items())

    def __len__(self) -> int:
        with self._lock:
            return len(self._owners)


# cold-start math fallback when no engine has advertised a measured
# fetch throughput yet (matches the weight plane's default)
DEFAULT_FETCH_BPS = 256e6


class ModelMap:
    """Which backends serve which model, plus the cold-start catalog —
    the model-aware half of routing (docs/model-fleet.md).

    Two information planes feed it:

      * **advertisements** — every /ready probe (and gossip merge)
        carries the backend's ``models`` list and its measured weight
        ``fetch_bps``; advertisements steer requests whose ``model``
        field names a served model onto the backends serving it;
      * **the catalog** — operator-declared ``{model: {warmup_ms,
        weight_bytes}}`` (the fleet's registered model set, cost-table
        ``warmup_ms`` semantics). A non-empty catalog turns on
        ENFORCEMENT: a model outside catalog+advertisements answers
        404, a known model with no live backend answers 503 with a
        Retry-After derived from ``warmup_ms`` + weight bytes over the
        measured fetch throughput.

    Without a catalog the map only steers — a deployment that never
    declared its model set keeps the legacy any-backend behavior for
    unknown names instead of 404ing them.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._by_url: Dict[str, frozenset] = {}
        self._catalog: Dict[str, Dict] = {}
        self._fetch_bps = 0.0  # EWMA over advertised measurements

    def load_catalog(self, catalog: Dict[str, Dict]):
        with self._lock:
            for name, spec in (catalog or {}).items():
                self._catalog[name] = {
                    "warmup_ms": float(spec.get("warmup_ms", 0.0)),
                    "weight_bytes": int(spec.get("weight_bytes", 0))}

    def advertise(self, url: str, models, fetch_bps=None):
        url = url.rstrip("/")
        if isinstance(models, (list, tuple)):
            served = frozenset(m for m in models
                               if isinstance(m, str) and m)
            with self._lock:
                self._by_url[url] = served
        if isinstance(fetch_bps, (int, float)) and fetch_bps > 0:
            with self._lock:
                self._fetch_bps = (fetch_bps if not self._fetch_bps
                                   else 0.8 * self._fetch_bps
                                   + 0.2 * fetch_bps)

    def forget(self, url: str):
        with self._lock:
            self._by_url.pop(url.rstrip("/"), None)

    def active(self) -> bool:
        with self._lock:
            return bool(self._by_url) or bool(self._catalog)

    def enforcing(self) -> bool:
        with self._lock:
            return bool(self._catalog)

    def cataloged(self, model: str) -> bool:
        with self._lock:
            return model in self._catalog

    def backends_for(self, model: str) -> frozenset:
        with self._lock:
            return frozenset(u for u, ms in self._by_url.items()
                             if model in ms)

    def models_of(self, url: str) -> frozenset:
        with self._lock:
            return self._by_url.get(url.rstrip("/"), frozenset())

    def backend_counts(self) -> Dict[str, int]:
        """{model: advertising-backend count} over catalog + served
        models — the per-model gauge's value set."""
        with self._lock:
            counts = {m: 0 for m in self._catalog}
            for ms in self._by_url.values():
                for m in ms:
                    counts[m] = counts.get(m, 0) + 1
            return counts

    def fetch_bps(self) -> float:
        with self._lock:
            return self._fetch_bps

    def retry_after(self, model: str) -> int:
        """Cold-start wait hint: catalog ``warmup_ms`` plus the time
        to fetch the model's weight bytes at the fleet's measured
        fetch throughput (EWMA of /ready advertisements; a default
        when nothing measured yet). Clamped to [1, 600]s."""
        with self._lock:
            spec = self._catalog.get(model) or {}
            bps = self._fetch_bps or DEFAULT_FETCH_BPS
        seconds = spec.get("warmup_ms", 0.0) / 1000.0 \
            + spec.get("weight_bytes", 0) / bps
        return max(1, min(600, int(seconds + 0.999)))

    def export(self) -> Dict[str, List[str]]:
        """{url: sorted models} — the gossip/debug view."""
        with self._lock:
            return {u: sorted(ms) for u, ms in self._by_url.items()}


class Router:
    def __init__(self, backends: List[Backend],
                 policy: str = "cache_aware",
                 health_interval: float = 10.0,
                 cb_threshold: Optional[int] = None,
                 cb_cooldown: Optional[float] = None,
                 registry: Optional[Registry] = None,
                 clock=time.monotonic):
        self.backends = backends
        # the time source the selection/breaker path reads (pick,
        # note_result, check_health_once); the simulator injects its
        # virtual clock so breaker cooldowns elapse in simulated time
        self._clock = clock
        for b in backends:  # router-level CB settings apply uniformly
            if cb_threshold is not None:
                b.cb_threshold = cb_threshold
            if cb_cooldown is not None:
                b.cb_cooldown = cb_cooldown
        self.policy = policy
        self.health_interval = health_interval
        self._rr = itertools.count()
        self._rng = random.Random(0)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._health_thread: Optional[threading.Thread] = None
        # every stat lives in the shared registry (leaf-locked
        # counters), so mutation is uniformly guarded — no more
        # direct dict bumps racing handler threads
        self.registry = registry or Registry()
        self._counters = {
            key: self.registry.counter(f"ome_router_{key}", help)
            for key, help in _COUNTER_HELP.items()}
        self._g_backends_up = self.registry.gauge(
            "ome_router_backends_up", "Backends passing health checks")
        self._g_backend_healthy = self.registry.gauge(
            "ome_router_backend_healthy",
            "Per-backend health bit (1 healthy)",
            labelnames=("backend", "pool"))
        self._g_backend_cb = self.registry.gauge(
            "ome_router_backend_circuit_state",
            "Per-backend breaker state: 0 closed, 1 half-open, 2 open",
            labelnames=("backend", "pool"))
        self._g_backends_draining = self.registry.gauge(
            "ome_router_backends_draining",
            "Backends currently draining (out of rotation, healthy)")
        self._g_backend_draining = self.registry.gauge(
            "ome_router_backend_draining",
            "Per-backend draining bit (1 draining)",
            labelnames=("backend", "pool"))
        self._g_backend_inflight = self.registry.gauge(
            "ome_router_backend_inflight",
            "Requests currently forwarded to this backend",
            labelnames=("backend", "pool"))
        # (url, pool) pairs exported on the last scrape — a removed
        # backend's gauges are zeroed once instead of lingering at
        # their final values forever (the registry has no child
        # removal, and a stale draining=1 would confuse autoscaling)
        self._gauge_keys: set = set()
        # fleet prefix directory: digest -> owning replica, fed by the
        # health probes' /ready piggyback, consulted per forward to
        # name a KV donor peer (cross-replica prefix reuse)
        self.prefix_directory = PrefixDirectory()
        self._g_prefix_dir = self.registry.gauge(
            "ome_router_prefix_directory_entries",
            "Prefix digests currently tracked by the fleet prefix "
            "directory")
        # per-class terminal outcomes at the front door — the SLO
        # rollup's availability signal (docs/slo.md). Children are
        # pre-created over the two fixed enums so cardinality is
        # bounded by construction.
        _fam_outcomes = self.registry.counter(
            "ome_router_class_outcomes_total",
            "Terminal request outcomes by priority class (ok = "
            "answered, including 4xx relays; error = 5xx/timeout/"
            "transport failures)",
            labelnames=("class", "result"))
        self._c_outcomes = {
            (cls, res): _fam_outcomes.labels(
                **{"class": cls, "result": res})
            for cls in PRIORITY_CLASSES
            for res in ("ok", "error")}
        # model-aware routing (docs/model-fleet.md): backend map fed
        # by /ready advertisements + gossip, catalog fed by
        # --model-catalog; per-model metric cardinality is bounded by
        # that operator-declared set plus what the fleet advertises
        self.model_map = ModelMap()
        self._c_model_requests = self.registry.counter(
            "ome_router_model_requests_total",
            "Requests routed by model field, per known model",
            labelnames=("model",))
        self._c_model_cold = self.registry.counter(
            "ome_router_model_cold_total",
            "Requests answered 503 + Retry-After because the model "
            "is known but has no live backend (cold start)",
            labelnames=("model",))
        self._c_model_unknown = self.registry.counter(
            "ome_router_model_unknown_total",
            "Requests answered 404 because the model is neither "
            "cataloged nor advertised by any backend")
        self._g_model_backends = self.registry.gauge(
            "ome_router_model_backends",
            "Backends currently advertising each model",
            labelnames=("model",))
        self._model_gauge_keys: set = set()

    @property
    def stats(self) -> Dict[str, float]:
        """Read-only snapshot of the registry-backed counters (the
        pre-telemetry dict API; mutate via inc(), never this view)."""
        return {key: c.value for key, c in self._counters.items()}

    def inc(self, key: str, by: float = 1):
        c = self._counters.get(key)
        if c is None:  # late-declared stat (tests, extensions)
            c = self._counters.setdefault(
                key, self.registry.counter(f"ome_router_{key}"))
        c.inc(by)

    def update_gauges(self):
        """Refresh the per-backend gauges (scrape-time; the breaker
        and health bits otherwise only change on traffic/probes)."""
        up = 0
        draining = 0
        with self._lock:
            views = [(b.url, b.pool, b.healthy, b.cb_state,
                      b.draining, b.inflight) for b in self.backends]
        seen = set()
        for url, pool, healthy, cb_state, drain, infl in views:
            up += bool(healthy)
            draining += bool(drain)
            seen.add((url, pool))
            self._g_backend_healthy.labels(
                backend=url, pool=pool).set(1 if healthy else 0)
            self._g_backend_cb.labels(backend=url, pool=pool).set(
                _CB_STATE_VALUE.get(cb_state, 2))
            self._g_backend_draining.labels(
                backend=url, pool=pool).set(1 if drain else 0)
            self._g_backend_inflight.labels(
                backend=url, pool=pool).set(infl)
        with self._lock:
            stale = self._gauge_keys - seen
            self._gauge_keys = seen
        for url, pool in stale:
            for g in (self._g_backend_healthy, self._g_backend_cb,
                      self._g_backend_draining,
                      self._g_backend_inflight):
                g.labels(backend=url, pool=pool).set(0)
        self._g_backends_up.set(up)
        self._g_backends_draining.set(draining)
        self._g_prefix_dir.set(len(self.prefix_directory))
        counts = self.model_map.backend_counts()
        for model, n in counts.items():
            # model names come from the operator catalog + engine
            # /ready advertisements, never from client payloads
            self._g_model_backends.labels(model=model).set(n)  # omelint: disable=metrics-label-cardinality -- catalog/advertised model names only, bounded by fleet config
        model_seen = set(counts)
        with self._lock:
            stale_models = self._model_gauge_keys - model_seen
            self._model_gauge_keys = model_seen
        for model in stale_models:
            self._g_model_backends.labels(model=model).set(0)  # omelint: disable=metrics-label-cardinality -- zeroing series created from the bounded catalog/advertised set above

    # -- membership ----------------------------------------------------
    # The autoscale controller's registration surface (POST/DELETE
    # /backends on RouterServer). Pure list mutation under _lock —
    # callers probe readiness BEFORE registering, so a freshly added
    # backend enters rotation immediately and the next health sweep
    # keeps it honest.

    def add_backend(self, url: str, pool: str = "engine") -> Backend:
        """Register a backend (idempotent on URL). Re-adding an
        existing URL cancels any drain — the autoscale controller
        re-registers a replica whose scale-down it aborted."""
        u = url.rstrip("/")
        with self._lock:
            for b in self.backends:
                if b.url == u:
                    b.draining = False
                    return b
            b = Backend(u, pool)
            self.backends.append(b)
            return b

    def remove_backend(self, url: str) -> bool:
        """Drop a backend from the set (after its drain completed).
        In-flight forwards hold their own Backend reference, so a
        racing request finishes normally; the backend simply cannot
        be picked again."""
        u = url.rstrip("/")
        with self._lock:
            for i, b in enumerate(self.backends):
                if b.url == u:
                    del self.backends[i]
                    self.prefix_directory.forget(u)
                    self.model_map.forget(u)
                    return True
        return False

    def backend_snapshot(self) -> List[dict]:
        """Consistent machine-readable view of the backend set (the
        GET /backends body; what the controller polls instead of
        parsing text exposition)."""
        with self._lock:
            return [{"url": b.url, "pool": b.pool,
                     "healthy": b.healthy, "draining": b.draining,
                     "inflight": b.inflight, "cb_state": b.cb_state}
                    for b in self.backends]

    # -- selection -----------------------------------------------------

    def _alive(self, pool: str) -> List[Backend]:
        with self._lock:
            return [b for b in self.backends
                    if b.pool == pool and b.healthy and not b.draining]

    def pick(self, pool: str, affinity_key: str = "",
             exclude: Optional[set] = None,
             model: Optional[str] = None) -> Optional[Backend]:
        # model steering: when the request names a model the fleet
        # serves, only backends advertising it are candidates
        allowed = (self.model_map.backends_for(model)
                   if model else None)
        now = self._clock()
        with self._lock:
            alive = [b for b in self.backends
                     if b.pool == pool and b.selectable(now)
                     and (not exclude or b.url not in exclude)
                     and (allowed is None or b.url in allowed)]
            if not alive:
                return None
            if self.policy == "random":
                chosen = self._rng.choice(alive)
            elif self.policy == "cache_aware" and affinity_key:
                # rendezvous (highest-random-weight) hashing: stable
                # under backend set changes, no ring state
                def weight(b: Backend) -> int:
                    return int.from_bytes(hashlib.blake2b(
                        f"{affinity_key}|{b.url}".encode(),
                        digest_size=8).digest(), "big")
                chosen = max(alive, key=weight)
            else:
                chosen = alive[next(self._rr) % len(alive)]
            if chosen.cb_state == "half_open":
                chosen.begin_probe()
            return chosen

    def note_result(self, backend: Backend, ok: bool):
        """Feed a request outcome into the backend's circuit breaker
        (and the boolean health bit the /health view exposes)."""
        opened = False
        with self._lock:
            if ok:
                backend.record_success()
            else:
                was_open = backend.cb_state == "open"
                backend.record_failure(self._clock())
                backend.healthy = False
                opened = backend.cb_state == "open" and not was_open
        if opened:
            # same registry-counter path as every other stat bump
            # (leaf-locked; kept outside _lock for uniformity)
            self.inc("circuit_open_total")

    def note_outcome(self, cls: str, ok: bool):
        """Record one terminal per-class request outcome — the SLO
        availability signal (docs/slo.md). client_gone outcomes are
        never reported here: the backend did nothing wrong and the
        client saw nothing, so they belong to neither side of the
        budget."""
        child = self._c_outcomes.get((cls, "ok" if ok else "error"))
        if child is not None:
            child.inc()

    def classify_model(self, model: str):
        """Route verdict for a request's ``model`` field:

        * ``("off", None)`` — model routing inactive for this name
          (no advertisements/catalog at all, or the name is unknown
          and no catalog demands enforcement): legacy any-backend;
        * ``("serving", urls)`` — at least one selectable backend
          advertises it: steer onto ``urls``;
        * ``("cold", urls)`` — known (cataloged, or advertised but
          every advertiser gone): 503 + Retry-After;
        * ``("unknown", None)`` — catalog enforcement on and the name
          is neither cataloged nor advertised: 404.
        """
        mm = self.model_map
        if not mm.active():
            return "off", None
        urls = mm.backends_for(model)
        if urls:
            now = self._clock()
            with self._lock:
                live = any(b.url in urls and b.selectable(now)
                           for b in self.backends)
            if live:
                return "serving", urls
            return "cold", urls
        if mm.cataloged(model):
            return "cold", frozenset()
        if mm.enforcing():
            return "unknown", None
        return "off", None

    def note_model_request(self, model: str):
        # only called on a "serving" verdict, so the label set is the
        # advertised-model universe — an arbitrary client-sent name
        # gets 404/off and never reaches a labeled series
        self._c_model_requests.labels(model=model).inc()  # omelint: disable=metrics-label-cardinality -- serving verdict gate bounds values to advertised models

    def note_model_cold(self, model: str):
        self._c_model_cold.labels(model=model).inc()  # omelint: disable=metrics-label-cardinality -- cold verdict gate bounds values to cataloged/advertised models

    def note_model_unknown(self):
        self._c_model_unknown.inc()

    def note_draining(self, backend: Backend):
        """The backend announced it is draining (503 + X-OME-Draining).
        Take it out of rotation WITHOUT penalty: the drain is
        deliberate, not a fault, so the breaker and the health bit are
        untouched — the /ready probe re-admits it if the drain is
        cancelled. Also releases a half-open probe slot so the drain
        cannot wedge the breaker."""
        with self._lock:
            backend.draining = True
            backend._probe_inflight = False

    def probe_aborted(self, backend: Backend):
        """A half-open probe request ended without a backend verdict
        (e.g. the CLIENT disconnected mid-probe). Release the probe
        slot; otherwise _probe_inflight stays latched and the backend
        can never be re-tested — it is wedged out of rotation until
        process restart."""
        with self._lock:
            backend._probe_inflight = False

    def adjust_inflight(self, backend: Backend, delta: int):
        """Bump a backend's in-flight counter under the router lock.
        Handler threads are concurrent (ThreadingHTTPServer): a bare
        ``backend.inflight += 1`` on the forwarding path is a
        read-modify-write that loses updates under contention and
        drifts the counter permanently."""
        with self._lock:
            backend.inflight += delta

    # -- health --------------------------------------------------------

    def check_health_once(self):
        with self._lock:
            targets = list(self.backends)
        for b in targets:
            res = self._probe_backend(b)
            # test overrides return the legacy (healthy, draining)
            # pair; the default carries the /ready body as a third
            # element — the prefix-directory piggyback
            healthy, draining = res[0], res[1]
            info = res[2] if len(res) > 2 else None
            with self._lock:
                b.healthy = healthy
                b.draining = draining
                b.last_checked = self._clock()
            if isinstance(info, dict):
                self.prefix_directory.update(
                    b.url, info.get("prefix_digests"))
                # model advertisement piggyback: which models this
                # backend serves + its measured weight-fetch
                # throughput (the Retry-After math's denominator)
                self.model_map.advertise(
                    b.url, info.get("models"),
                    info.get("fetch_bps"))

    @staticmethod
    def _probe_backend(b: Backend):
        return probe_backend_info(b.url)

    def start_health_loop(self):
        def loop():
            while not self._stop.wait(self.health_interval):
                self.check_health_once()
        self._health_thread = threading.Thread(
            target=loop, name="router-health", daemon=True)
        self._health_thread.start()

    def stop(self):
        self._stop.set()


class RetryBudget:
    """Finagle-style token bucket bounding retry amplification: each
    incoming request deposits `ratio` tokens (plus a small constant
    burst floor to keep single-request failover working at low
    traffic); each retry withdraws one. A pool-wide outage therefore
    costs at most (1 + ratio) x offered load, not retries x load."""

    def __init__(self, ratio: float = 0.2, burst: float = 10.0):
        self.ratio = ratio
        self.burst = burst
        self._tokens = burst
        self._lock = threading.Lock()

    def deposit(self):
        with self._lock:
            self._tokens = min(self._tokens + self.ratio,
                               self.burst)

    def withdraw(self) -> bool:
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False


def affinity_from_payload(payload: dict) -> str:
    """Prefix-affinity key: the leading content of the request, so a
    continuing conversation maps to the replica already holding its
    KV prefix."""
    if "prompt" in payload:
        p = payload["prompt"]
        p = p if isinstance(p, str) else "".join(map(str, p))
        return p[:256]
    msgs = payload.get("messages")
    if msgs:
        return json.dumps(msgs[:2])[:256]
    return ""


class RouterServer:
    def __init__(self, router: Router, host: str = "0.0.0.0",
                 port: int = 0, retries: int = 2,
                 retry_backoff: float = 0.05,
                 retry_budget_ratio: float = 0.2,
                 request_log=None, span_log=None,
                 debug_endpoints: bool = False):
        self.router = router
        self.retries = retries
        self.retry_backoff = retry_backoff
        # gates the introspection/admin surface (GET/POST/DELETE
        # /backends), same contract as the engine's /debug/state:
        # off by default, 403 when disabled
        self.debug_endpoints = debug_endpoints
        # fleet SLO rollup (docs/slo.md): attached by main() when
        # --slo-spec is given; GET /slo answers 404 until then
        self.slo_rollup = None
        self.budget = RetryBudget(ratio=retry_budget_ratio)
        self._jitter = random.Random(1)
        self.request_log = _coerce_reqlog(request_log)
        # span timeline (docs/tracing-timeline.md): one router.request
        # root span per proxied request plus one router.attempt span
        # per forward — the attempt's span id IS the traceparent child
        # the backend receives, so engine spans nest under the exact
        # attempt that carried them
        self.span_log = tracing.coerce_span_log(span_log,
                                                component="router")
        self._h_request = router.registry.histogram(
            "ome_router_request_seconds",
            "End-to-end proxied request seconds (retries included)")
        # per-class accounting at the front door: children are
        # pre-created from the fixed class enum so a hostile header
        # can never mint new label values (cardinality stays bounded)
        _fam_class = router.registry.counter(
            "ome_router_class_requests_total",
            "Completion requests proxied, by priority class",
            labelnames=("class",))
        self._c_class = {c: _fam_class.labels(**{"class": c})
                         for c in PRIORITY_CLASSES}
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _json(self, code: int, obj, headers=None):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _backends_guard(self) -> bool:
                """403 unless --debug-endpoints enabled the admin
                surface; True when the caller may proceed."""
                if outer.debug_endpoints:
                    return True
                self._json(403, {"error": "debug endpoints disabled "
                                          "(enable --debug-endpoints)"})
                return False

            def do_GET(self):
                if self.path in ("/health", "/healthz"):
                    snap = outer.router.backend_snapshot()
                    up = any(b["healthy"] for b in snap)
                    return self._json(200 if up else 503, {
                        "status": "ok" if up else "no healthy backends",
                        "backends": [
                            {k: b[k] for k in
                             ("url", "pool", "healthy", "draining")}
                            for b in snap]})
                if self.path == "/backends":
                    # machine-readable pool membership for the
                    # autoscale controller and tests (guarded like the
                    # engine's /debug/state)
                    if not self._backends_guard():
                        return None
                    return self._json(200, {
                        "backends": outer.router.backend_snapshot()})
                if self.path == "/slo":
                    # fleet SLO attainment / budget / alert state
                    # (docs/slo.md), guarded like /backends
                    if not self._backends_guard():
                        return None
                    if outer.slo_rollup is None:
                        return self._json(404, {
                            "error": "slo rollup not configured "
                                     "(start with --slo-spec)"})
                    return self._json(200, outer.slo_rollup.report())
                if self.path == "/metrics":
                    outer.router.update_gauges()
                    body = outer.router.registry.render().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    return self.wfile.write(body)
                # pass through model listings etc. to any backend
                return self._proxy(b"", stream=False)

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n)
                if self.path == "/backends":
                    return self._backends_mutate(body, add=True)
                try:
                    payload = json.loads(body or b"{}")
                except ValueError:
                    payload = {}
                cls = None
                if self.path in ("/v1/completions",
                                 "/v1/chat/completions"):
                    # account the class here but forward the request
                    # verbatim: an unknown value counts as the default
                    # class and the ENGINE answers the 400 (the router
                    # never rewrites or silently drops tenant intent)
                    try:
                        cls = coerce_priority(
                            self.headers.get("X-OME-Priority")
                            or payload.get("priority"))
                    except ValueError:
                        cls = DEFAULT_PRIORITY
                    outer._c_class[cls].inc()
                stream = bool(payload.get("stream"))
                mdl = payload.get("model")
                self._proxy(body, stream=stream,
                            affinity=affinity_from_payload(payload),
                            cls=cls,
                            model=mdl if isinstance(mdl, str) else None)

            def do_DELETE(self):
                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n)
                if self.path == "/backends":
                    return self._backends_mutate(body, add=False)
                return self._json(404, {"error": "not found"})

            def _backends_mutate(self, body: bytes, add: bool):
                """POST /backends {"url":..,"pool":..} registers a
                backend; DELETE /backends {"url":..} removes one.
                The autoscale pool calls these after spawning a ready
                engine / after a drained engine exits."""
                if not self._backends_guard():
                    return None
                try:
                    payload = json.loads(body or b"{}")
                except ValueError:
                    payload = {}
                url = payload.get("url")
                if not url:
                    return self._json(400, {"error": "missing 'url'"})
                if add:
                    b = outer.router.add_backend(
                        url, payload.get("pool") or "engine")
                    return self._json(200, {
                        "ok": True, "url": b.url, "pool": b.pool})
                removed = outer.router.remove_backend(url)
                return self._json(200 if removed else 404, {
                    "ok": removed, "url": url.rstrip("/")})

            def _pick_pool(self) -> str:
                # explicit steer via header; else engine pool, falling
                # back to decoders when no engine is configured/healthy
                want = (self.headers.get("X-OME-Pool") or "engine")
                if outer.router._alive(want):
                    return want
                other = "decoder" if want == "engine" else "engine"
                return other if outer.router._alive(other) else want

            def _deadline(self) -> Optional[float]:
                """X-Request-Deadline: absolute epoch seconds."""
                hdr = self.headers.get("X-Request-Deadline")
                if not hdr:
                    return None
                try:
                    return float(hdr)
                except ValueError:
                    return None

            def _proxy(self, body: bytes, stream: bool,
                       affinity: str = "",
                       cls: Optional[str] = None,
                       model: Optional[str] = None):
                # request-lifecycle tracing: adopt the caller's
                # traceparent or mint a fresh trace; every forwarded
                # hop carries a CHILD span of this context, and both
                # router and engine request logs share the trace id
                ctx = tracing.from_headers(self.headers)
                t0 = time.monotonic()
                outcome = {"backend": None, "pool": None,
                           "status": "error", "retries": 0,
                           "class": cls}
                # root timeline span: reuses the context's span id, so
                # per-attempt child spans (and through them the engine
                # spans) all parent on this one record
                span = None
                if outer.span_log.enabled:
                    span = tracing.Span("router.request",
                                        trace_id=ctx.trace_id,
                                        span_id=ctx.span_id,
                                        start_mono=t0)
                    span.set(path=self.path)
                try:
                    return self._route(body, stream, affinity, ctx,
                                       outcome, model=model)
                finally:
                    dur = time.monotonic() - t0
                    outer._h_request.observe(dur)
                    if cls is not None \
                            and outcome["status"] != "client_gone":
                        # availability: everything the router answered
                        # is good except its own failure statuses
                        outer.router.note_outcome(
                            cls, outcome["status"] == "ok")
                    if span is not None:
                        span.set(pool=outcome["pool"],
                                 backend=outcome["backend"],
                                 status=outcome["status"],
                                 retries=outcome["retries"])
                        span.end(t0 + dur)
                        outer.span_log.write(span)
                    if outer.request_log.enabled:
                        outer.request_log.write({
                            "component": "router",
                            "trace_id": ctx.trace_id,
                            "span_id": ctx.span_id,
                            "path": self.path,
                            "pool": outcome["pool"],
                            "backend": outcome["backend"],
                            "status": outcome["status"],
                            "retries": outcome["retries"],
                            "duration_s": round(dur, 6)})

            def _route(self, body: bytes, stream: bool, affinity: str,
                       ctx, outcome: dict,
                       model: Optional[str] = None):
                outer.router.inc("requests_total")
                outer.budget.deposit()
                deadline = self._deadline()
                # model-aware gate (docs/model-fleet.md): unknown
                # model 404s, a known-but-cold model answers 503 with
                # a Retry-After the weight plane's measured fetch
                # throughput backs — the client knows when to retry
                # instead of hammering a fleet that is still fetching
                if model:
                    verdict, _ = outer.router.classify_model(model)
                    if verdict == "unknown":
                        outer.router.note_model_unknown()
                        outcome["status"] = "unknown_model"
                        return self._json(404, {
                            "error": f"model {model!r} is not served "
                                     "by this fleet",
                            "model": model})
                    if verdict == "cold":
                        ra = outer.router.model_map.retry_after(model)
                        outer.router.note_model_cold(model)
                        if outer.span_log.enabled:
                            cspan = tracing.Span(
                                "router.cold_start",
                                trace_id=ctx.trace_id,
                                parent_id=ctx.span_id)
                            cspan.set(model=model, retry_after=ra)
                            outer.span_log.write(cspan)
                        outcome["status"] = "cold_start"
                        return self._json(503, {
                            "error": f"model {model!r} is cold "
                                     "(no live backend yet)",
                            "model": model, "retry_after": ra},
                            headers={"Retry-After": str(ra)})
                    if verdict == "serving":
                        outer.router.note_model_request(model)
                    else:
                        model = None  # routing off for this name
                pool = self._pick_pool()
                outcome["pool"] = pool
                # fleet prefix directory: if some replica owns this
                # request's prefix, remember it — a forward landing
                # ELSEWHERE names the owner as a KV donor peer
                peer_hint = None
                if affinity and outer.router.policy == "cache_aware":
                    peer_hint = outer.router.prefix_directory.lookup(
                        prefix_digest(affinity))
                    if peer_hint is not None:
                        outer.router.inc("prefix_directory_hits_total")
                tried: set = set()
                last_err = "no healthy backends"
                # `failures` counts TRANSPORT failures only; a draining
                # redirect is free (no retry token, no backoff, no
                # breaker hit). Terminates regardless: every iteration
                # adds the picked backend to `tried`, and pick()
                # excludes tried backends.
                failures = 0
                need_backoff = False
                while failures <= outer.retries:
                    if deadline is not None and time.time() >= deadline:
                        # the client stopped caring: do not burn a
                        # backend slot (or a retry token) on it
                        outer.router.inc("deadline_shed_total")
                        outcome["status"] = "deadline"
                        return self._json(504, {
                            "error": "request deadline exceeded"})
                    if need_backoff:
                        need_backoff = False
                        if not outer.budget.withdraw():
                            # retry budget exhausted: fail fast rather
                            # than amplify a pool-wide outage
                            outer.router.inc(
                                "retry_budget_exhausted_total")
                            break
                        delay = (outer.retry_backoff
                                 * (2 ** (failures - 1))
                                 * (1 + outer._jitter.random()))
                        time.sleep(delay)
                    backend = outer.router.pick(pool, affinity,
                                                exclude=tried,
                                                model=model)
                    if backend is None:
                        break
                    tried.add(backend.url)
                    outcome["backend"] = backend.url
                    outcome["retries"] = failures
                    # the child context is minted BEFORE the forward so
                    # the attempt span can claim its span id — engine
                    # records parenting on the forwarded traceparent
                    # then nest under this exact attempt
                    child = ctx.child()
                    aspan = None
                    if outer.span_log.enabled:
                        aspan = tracing.Span("router.attempt",
                                             trace_id=ctx.trace_id,
                                             parent_id=ctx.span_id,
                                             span_id=child.span_id)
                        aspan.set(backend=backend.url,
                                  retries=failures)
                    try:
                        result = self._forward(
                            backend, body, stream, deadline,
                            trace=child,
                            prefix_peer=(peer_hint
                                         if peer_hint != backend.url
                                         else None))
                        outer.router.note_result(backend, ok=True)
                        outcome["status"] = "ok"
                        if aspan is not None:
                            outer.span_log.write(aspan.set(status="ok"))
                        return result
                    except _BackendDraining:
                        # deliberate shutdown, not a fault: take the
                        # backend out of rotation and move on without
                        # touching the breaker or the retry budget
                        outer.router.note_draining(backend)
                        outer.router.inc("draining_skips_total")
                        log.info("backend %s draining; redirecting",
                                 backend.url)
                        if aspan is not None:
                            outer.span_log.write(
                                aspan.set(status="draining"))
                        continue
                    except _ClientGone:
                        # the CLIENT went away: nothing to retry, and
                        # the backend did nothing wrong — but release
                        # its half-open probe slot if this was a probe
                        outer.router.probe_aborted(backend)
                        outcome["status"] = "client_gone"
                        if aspan is not None:
                            outer.span_log.write(
                                aspan.set(status="client_gone"))
                        return None
                    except _ResponseStarted as e:
                        # bytes already reached the client: a retry
                        # would interleave two responses on one socket
                        outer.router.note_result(backend, ok=False)
                        log.warning("backend %s died mid-response: %s",
                                    backend.url, e)
                        try:
                            self.wfile.write(b"0\r\n\r\n")
                        except OSError:
                            pass
                        self.close_connection = True
                        outcome["status"] = "stream_abort"
                        if aspan is not None:
                            outer.span_log.write(
                                aspan.set(status="stream_abort"))
                        return None
                    except (urllib.error.URLError, OSError,
                            ConnectionError) as e:
                        last_err = str(e)
                        outer.router.note_result(backend, ok=False)
                        outer.router.inc("retries_total")
                        log.warning("backend %s failed (%s); retrying",
                                    backend.url, e)
                        if aspan is not None:
                            outer.span_log.write(aspan.set(
                                status="error", error=str(e)))
                        failures += 1
                        need_backoff = True
                outer.router.inc("no_backend_total")
                outcome["status"] = "no_backend"
                self._json(503, {"error": f"routing failed: {last_err}"},
                           headers={"Retry-After": "1"})

            def _client_write(self, data: bytes):
                try:
                    self.wfile.write(data)
                except (OSError, ConnectionError) as e:
                    raise _ClientGone(str(e)) from e

            def _forward(self, backend: Backend, body: bytes,
                         stream: bool, deadline: Optional[float] = None,
                         trace=None, prefix_peer: Optional[str] = None):
                from .. import faults

                # deterministic fault injection: an armed rule makes
                # this backend look connection-dead (URLError), which
                # exercises failover + the circuit breaker
                faults.fire("router_forward", key=backend.url,
                            exc=urllib.error.URLError)
                headers = {"Content-Type": "application/json"}
                if trace is not None:
                    headers[tracing.TRACEPARENT_HEADER] = trace.header()
                pri = self.headers.get("X-OME-Priority")
                if pri:
                    # the priority class propagates like the deadline:
                    # the engine's admission/scheduling decisions need
                    # the tenant class the client declared
                    headers["X-OME-Priority"] = pri
                if prefix_peer:
                    # cross-replica prefix reuse: the chosen backend
                    # does not own this prefix — name the replica that
                    # does, so it can fetch the KV over /pd/prefill
                    # (engine/peering.py) instead of recomputing it
                    headers["X-OME-Prefix-Peer"] = prefix_peer
                    outer.router.inc(
                        "prefix_directory_peer_fetches_total")
                timeout = 600.0
                if deadline is not None:
                    # propagate the client deadline downstream and
                    # bound our own wait by it
                    headers["X-Request-Deadline"] = repr(deadline)
                    timeout = max(min(timeout,
                                      deadline - time.time()), 0.05)
                req = urllib.request.Request(
                    backend.url + self.path, data=body or None,
                    method=self.command, headers=headers)
                outer.router.adjust_inflight(backend, 1)
                try:
                    resp = urllib.request.urlopen(req, timeout=timeout)
                except urllib.error.HTTPError as e:
                    if e.code == 503 and e.headers.get("X-OME-Draining"):
                        # graceful shutdown announcement, not a fault
                        e.close()
                        raise _BackendDraining(backend.url) from e
                    if e.code >= 500:
                        # a 5xx is a BACKEND failure (dead scheduler,
                        # injected fault): close the response and let
                        # the retry loop fail over + trip the breaker
                        e.close()
                        raise urllib.error.URLError(
                            f"backend returned {e.code}") from e
                    # 4xx are APPLICATION responses (bad request,
                    # model not found, 429 overload): relay verbatim,
                    # Retry-After included, don't failover
                    data = e.read()
                    self.send_response(e.code)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(data)))
                    ra = e.headers.get("Retry-After")
                    if ra:
                        self.send_header("Retry-After", ra)
                    self.end_headers()
                    self._client_write(data)
                    return None
                finally:
                    outer.router.adjust_inflight(backend, -1)
                with resp:
                    if stream:
                        self.send_response(resp.status)
                        self.send_header("Content-Type",
                                         resp.headers.get("Content-Type",
                                                          "text/event-stream"))
                        self.send_header("Transfer-Encoding", "chunked")
                        self.end_headers()
                        started = True
                        # real SSE clients (the replay client
                        # included) hang up the moment they read the
                        # `data: [DONE]` sentinel, without draining
                        # the trailing blank line or the chunked
                        # terminator — once the sentinel is delivered
                        # the request was SERVED, and classifying it
                        # client_gone would poison the availability
                        # SLO (docs/slo.md)
                        done_sent = False
                        while True:
                            try:
                                raw = resp.readline()
                            except (urllib.error.URLError, OSError,
                                    ConnectionError) as e:
                                raise _ResponseStarted(str(e)) from e
                            if not raw:
                                break
                            try:
                                self._client_write(
                                    f"{len(raw):x}\r\n".encode() + raw
                                    + b"\r\n")
                                self.wfile.flush()
                            except (_ClientGone, OSError,
                                    ConnectionError) as e:
                                if done_sent:
                                    break
                                if isinstance(e, _ClientGone):
                                    raise
                                raise _ClientGone(str(e)) from e
                            if raw.strip() == b"data: [DONE]":
                                done_sent = True
                        try:
                            self._client_write(b"0\r\n\r\n")
                        except _ClientGone:
                            # upstream is drained and every body byte
                            # was relayed: a client that hangs up
                            # between the last event and the
                            # terminating chunk still received the
                            # whole response — served, not abandoned
                            pass
                        return None
                    try:
                        data = resp.read()
                    except (urllib.error.URLError, OSError,
                            ConnectionError) as e:
                        # nothing sent to the client yet: retryable
                        raise urllib.error.URLError(str(e)) from e
                    self.send_response(resp.status)
                    self.send_header("Content-Type",
                                     resp.headers.get("Content-Type",
                                                      "application/json"))
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self._client_write(data)
                    return None

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "RouterServer":
        self.router.start_health_loop()
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        name="ome-router", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self.router.stop()
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
        self.request_log.close()
        self.span_log.close()


def discover_backends(client, namespace: str, selector: Dict[str, str],
                      pool: str, port: int = 8080) -> List[Backend]:
    """Service discovery through the shared client: Services matching
    the selector labels become backends at their cluster DNS names
    (the RouterConfig engine-selector/decoder-selector contract)."""
    from ..core.k8s import Service
    out = []
    for svc in client.list(Service, namespace=namespace,
                           label_selector=selector):
        svc_port = port
        if svc.spec.ports:
            svc_port = svc.spec.ports[0].port
        out.append(Backend(
            f"http://{svc.metadata.name}.{svc.metadata.namespace}"
            f".svc.cluster.local:{svc_port}", pool))
    return out


def _parse_selector(s: str) -> Dict[str, str]:
    return dict(kv.split("=", 1) for kv in s.split(",") if "=" in kv)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ome-router")
    p.add_argument("--backend", action="append", default=[],
                   help="engine URL (repeatable); pool prefix with "
                        "'decoder=' routes to the decode pool")
    p.add_argument("--policy", default="cache_aware",
                   choices=("cache_aware", "round_robin", "random"))
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--bind", default="0.0.0.0")
    p.add_argument("--health-interval", type=float, default=10.0)
    p.add_argument("--retries", type=int, default=2,
                   help="max failover attempts per request (budgeted: "
                        "retries also draw from a token bucket "
                        "replenished by request volume)")
    p.add_argument("--retry-backoff", type=float, default=0.05,
                   help="base delay before retry N doubles from here, "
                        "with jitter")
    p.add_argument("--cb-threshold", type=int, default=3,
                   help="consecutive request failures that open a "
                        "backend's circuit breaker")
    p.add_argument("--cb-cooldown", type=float, default=1.0,
                   help="initial circuit-open cooldown seconds "
                        "(doubles per trip, capped at 30s); a single "
                        "half-open probe re-admits the backend")
    p.add_argument("--faults", default=None,
                   help="deterministic fault-injection spec "
                        "(ome_tpu/faults.py grammar); also via "
                        "OME_FAULTS")
    p.add_argument("--debug-endpoints", action="store_true",
                   help="enable the guarded admin surface: GET "
                        "/backends (machine-readable membership) and "
                        "POST/DELETE /backends (autoscale "
                        "registration); 403 otherwise")
    p.add_argument("--model-catalog", default=None,
                   help="model catalog JSON ({model: {warmup_ms, "
                        "weight_bytes}}): declares the fleet's model "
                        "set and turns on model-aware enforcement — "
                        "unknown model 404, known-but-cold 503 + "
                        "Retry-After (docs/model-fleet.md)")
    p.add_argument("--slo-spec", default=None,
                   help="SLO spec JSON (config/slo.json format): "
                        "starts the fleet rollup loop and serves "
                        "GET /slo + ome_slo_* metrics (docs/slo.md)")
    p.add_argument("--slo-interval", type=float, default=5.0,
                   help="seconds between fleet SLO rollup scrapes")
    p.add_argument("--request-log", default=None,
                   help="JSONL request-log path (one record per "
                        "proxied request with trace id, backend, "
                        "retries, duration; docs/observability.md)")
    p.add_argument("--span-log", default=None,
                   help="span-timeline JSONL path (router.request / "
                        "router.attempt spans, joinable with engine "
                        "span logs by trace id via "
                        "scripts/trace_export.py; "
                        "docs/tracing-timeline.md)")
    p.add_argument("--engine-selector", default=None,
                   help="k8s label selector for engine Services "
                        "(k=v[,k=v]); requires --in-cluster/--kube-*")
    p.add_argument("--decoder-selector", default=None)
    p.add_argument("--namespace", default="default")
    p.add_argument("--kubeconfig", default=None)
    p.add_argument("--kube-server", default=None)
    p.add_argument("--in-cluster", action="store_true")
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    if args.faults:
        from .. import faults
        faults.install(args.faults)
        log.warning("fault injection ACTIVE: %s", args.faults)
    backends = []
    for spec in args.backend:
        # only known pool prefixes split — URLs may contain '='
        if spec.startswith("decoder="):
            backends.append(Backend(spec[len("decoder="):], "decoder"))
        elif spec.startswith("engine="):
            backends.append(Backend(spec[len("engine="):], "engine"))
        else:
            backends.append(Backend(spec, "engine"))
    if args.engine_selector or args.decoder_selector:
        from ..cmd.manager import build_client
        client = build_client(args)
        if args.engine_selector:
            backends += discover_backends(
                client, args.namespace,
                _parse_selector(args.engine_selector), "engine")
        if args.decoder_selector:
            backends += discover_backends(
                client, args.namespace,
                _parse_selector(args.decoder_selector), "decoder")
        log.info("discovered %d backends via selectors", len(backends))
    if not backends:
        p.error("at least one --backend or --engine-selector is required")
    router = Router(backends, policy=args.policy,
                    health_interval=args.health_interval,
                    cb_threshold=args.cb_threshold,
                    cb_cooldown=args.cb_cooldown)
    if args.model_catalog:
        with open(args.model_catalog, "r", encoding="utf-8") as f:
            router.model_map.load_catalog(json.load(f))
        log.info("model catalog loaded: %s (enforcement on)",
                 args.model_catalog)
    router.check_health_once()
    srv = RouterServer(router, host=args.bind, port=args.port,
                       retries=args.retries,
                       retry_backoff=args.retry_backoff,
                       request_log=args.request_log,
                       span_log=args.span_log,
                       debug_endpoints=args.debug_endpoints).start()
    if args.slo_spec:
        from ..autoscale.scrape import SharedScraper
        from ..slo import FleetRollup
        from ..slo import load as load_slo
        from ..slo.rollup import start_thread as start_slo_thread
        scraper = SharedScraper(clock=time.monotonic,
                                max_age=args.slo_interval / 2.0)
        srv.slo_rollup = FleetRollup(
            load_slo(args.slo_spec), clock=time.monotonic,
            fetch_fn=scraper.fetch,
            backends_fn=router.backend_snapshot,
            registry=router.registry,
            local_samples_fn=router.registry.snapshot)
        start_slo_thread(srv.slo_rollup, args.slo_interval)
        log.info("slo rollup active: %s every %.1fs",
                 args.slo_spec, args.slo_interval)
    log.info("router on :%d over %d backends (policy=%s)", srv.port,
             len(backends), args.policy)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        srv.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
