"""Router implementation.

Design (vs the reference's sglang-router, which it deploys as the
router component — SURVEY.md §2.9 "PD disaggregation"):

  * backends come from static --backend flags or from watching
    Endpoints-like service discovery through the shared client
    (component selectors, the same contract RouterConfig carries in
    the catalog: engine-selector / decoder-selector);
  * policies: `cache_aware` (consistent prefix-hash affinity, so a
    conversation keeps hitting the replica whose KV cache already
    holds its prefix), `round_robin`, `random`;
  * health: background probing of each backend's /health; unhealthy
    backends leave the rotation, failed requests retry on the next
    backend;
  * streaming passthrough: SSE bodies relay chunk-by-chunk.

PD note: the KV handoff itself lives in the engines — decode nodes
pull the prefix KV from the prefill pool over /pd/prefill
(engine/pd.py wire format + RemotePrefillEngine); the router's PD job
is steering — completions go to the DECODE pool (whose engines fetch
prefill remotely), and cache-aware affinity keeps same-prefix traffic
on the same prefill node so its radix prefix cache can hit.
"""

from __future__ import annotations

import argparse
import hashlib
import itertools
import json
import logging
import random
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

log = logging.getLogger("ome.router")


class _ClientGone(Exception):
    """The requesting client disconnected; abort without failover."""


class _ResponseStarted(Exception):
    """Backend failed after response bytes reached the client —
    failover would corrupt the stream."""


class Backend:
    def __init__(self, url: str, pool: str = "engine"):
        self.url = url.rstrip("/")
        self.pool = pool
        self.healthy = True
        self.inflight = 0
        self.last_checked = 0.0

    def __repr__(self):
        return f"Backend({self.url}, {self.pool}, " \
               f"{'up' if self.healthy else 'down'})"


class Router:
    def __init__(self, backends: List[Backend],
                 policy: str = "cache_aware",
                 health_interval: float = 10.0):
        self.backends = backends
        self.policy = policy
        self.health_interval = health_interval
        self._rr = itertools.count()
        self._rng = random.Random(0)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._health_thread: Optional[threading.Thread] = None
        self.stats: Dict[str, float] = {
            "requests_total": 0, "retries_total": 0,
            "no_backend_total": 0}

    def inc(self, key: str, by: float = 1):
        with self._lock:  # handler threads are concurrent
            self.stats[key] = self.stats.get(key, 0) + by

    # -- selection -----------------------------------------------------

    def _alive(self, pool: str) -> List[Backend]:
        return [b for b in self.backends
                if b.pool == pool and b.healthy]

    def pick(self, pool: str, affinity_key: str = "",
             exclude: Optional[set] = None) -> Optional[Backend]:
        with self._lock:
            alive = [b for b in self._alive(pool)
                     if not exclude or b.url not in exclude]
            if not alive:
                return None
            if self.policy == "random":
                return self._rng.choice(alive)
            if self.policy == "cache_aware" and affinity_key:
                # rendezvous (highest-random-weight) hashing: stable
                # under backend set changes, no ring state
                def weight(b: Backend) -> int:
                    return int.from_bytes(hashlib.blake2b(
                        f"{affinity_key}|{b.url}".encode(),
                        digest_size=8).digest(), "big")
                return max(alive, key=weight)
            return alive[next(self._rr) % len(alive)]

    # -- health --------------------------------------------------------

    def check_health_once(self):
        for b in list(self.backends):
            try:
                with urllib.request.urlopen(b.url + "/health",
                                            timeout=5) as resp:
                    b.healthy = resp.status == 200
            except Exception:
                b.healthy = False
            b.last_checked = time.time()

    def start_health_loop(self):
        def loop():
            while not self._stop.wait(self.health_interval):
                self.check_health_once()
        self._health_thread = threading.Thread(
            target=loop, name="router-health", daemon=True)
        self._health_thread.start()

    def stop(self):
        self._stop.set()


def affinity_from_payload(payload: dict) -> str:
    """Prefix-affinity key: the leading content of the request, so a
    continuing conversation maps to the replica already holding its
    KV prefix."""
    if "prompt" in payload:
        p = payload["prompt"]
        p = p if isinstance(p, str) else "".join(map(str, p))
        return p[:256]
    msgs = payload.get("messages")
    if msgs:
        return json.dumps(msgs[:2])[:256]
    return ""


class RouterServer:
    def __init__(self, router: Router, host: str = "0.0.0.0",
                 port: int = 0, retries: int = 2):
        self.router = router
        self.retries = retries
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _json(self, code: int, obj):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path in ("/health", "/healthz"):
                    up = any(b.healthy for b in outer.router.backends)
                    return self._json(200 if up else 503, {
                        "status": "ok" if up else "no healthy backends",
                        "backends": [
                            {"url": b.url, "pool": b.pool,
                             "healthy": b.healthy}
                            for b in outer.router.backends]})
                if self.path == "/metrics":
                    lines = []
                    for k, v in outer.router.stats.items():
                        lines.append(f"# TYPE ome_router_{k} counter")
                        lines.append(f"ome_router_{k} {v}")
                    up = sum(b.healthy for b in outer.router.backends)
                    lines.append("# TYPE ome_router_backends_up gauge")
                    lines.append(f"ome_router_backends_up {up}")
                    body = ("\n".join(lines) + "\n").encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    return self.wfile.write(body)
                # pass through model listings etc. to any backend
                return self._proxy(b"", stream=False)

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n)
                try:
                    payload = json.loads(body or b"{}")
                except ValueError:
                    payload = {}
                stream = bool(payload.get("stream"))
                self._proxy(body, stream=stream,
                            affinity=affinity_from_payload(payload))

            def _pick_pool(self) -> str:
                # explicit steer via header; else engine pool, falling
                # back to decoders when no engine is configured/healthy
                want = (self.headers.get("X-OME-Pool") or "engine")
                if outer.router._alive(want):
                    return want
                other = "decoder" if want == "engine" else "engine"
                return other if outer.router._alive(other) else want

            def _proxy(self, body: bytes, stream: bool,
                       affinity: str = ""):
                outer.router.inc("requests_total")
                pool = self._pick_pool()
                tried: set = set()
                last_err = "no healthy backends"
                for attempt in range(outer.retries + 1):
                    backend = outer.router.pick(pool, affinity,
                                                exclude=tried)
                    if backend is None:
                        break
                    tried.add(backend.url)
                    try:
                        return self._forward(backend, body, stream)
                    except _ClientGone:
                        # the CLIENT went away: nothing to retry, and
                        # the backend did nothing wrong
                        return None
                    except _ResponseStarted as e:
                        # bytes already reached the client: a retry
                        # would interleave two responses on one socket
                        backend.healthy = False
                        log.warning("backend %s died mid-response: %s",
                                    backend.url, e)
                        try:
                            self.wfile.write(b"0\r\n\r\n")
                        except OSError:
                            pass
                        self.close_connection = True
                        return None
                    except (urllib.error.URLError, OSError,
                            ConnectionError) as e:
                        last_err = str(e)
                        backend.healthy = False
                        outer.router.inc("retries_total")
                        log.warning("backend %s failed (%s); retrying",
                                    backend.url, e)
                outer.router.inc("no_backend_total")
                self._json(503, {"error": f"routing failed: {last_err}"})

            def _client_write(self, data: bytes):
                try:
                    self.wfile.write(data)
                except (OSError, ConnectionError) as e:
                    raise _ClientGone(str(e)) from e

            def _forward(self, backend: Backend, body: bytes,
                         stream: bool):
                req = urllib.request.Request(
                    backend.url + self.path, data=body or None,
                    method=self.command,
                    headers={"Content-Type": "application/json"})
                backend.inflight += 1
                try:
                    resp = urllib.request.urlopen(req, timeout=600)
                except urllib.error.HTTPError as e:
                    # HTTP errors are APPLICATION responses (4xx):
                    # relay, don't failover
                    data = e.read()
                    self.send_response(e.code)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self._client_write(data)
                    return None
                finally:
                    backend.inflight -= 1
                with resp:
                    if stream:
                        self.send_response(resp.status)
                        self.send_header("Content-Type",
                                         resp.headers.get("Content-Type",
                                                          "text/event-stream"))
                        self.send_header("Transfer-Encoding", "chunked")
                        self.end_headers()
                        started = True
                        while True:
                            try:
                                raw = resp.readline()
                            except (urllib.error.URLError, OSError,
                                    ConnectionError) as e:
                                raise _ResponseStarted(str(e)) from e
                            if not raw:
                                break
                            self._client_write(
                                f"{len(raw):x}\r\n".encode() + raw
                                + b"\r\n")
                            try:
                                self.wfile.flush()
                            except (OSError, ConnectionError) as e:
                                raise _ClientGone(str(e)) from e
                        self._client_write(b"0\r\n\r\n")
                        return None
                    try:
                        data = resp.read()
                    except (urllib.error.URLError, OSError,
                            ConnectionError) as e:
                        # nothing sent to the client yet: retryable
                        raise urllib.error.URLError(str(e)) from e
                    self.send_response(resp.status)
                    self.send_header("Content-Type",
                                     resp.headers.get("Content-Type",
                                                      "application/json"))
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self._client_write(data)
                    return None

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "RouterServer":
        self.router.start_health_loop()
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        name="ome-router", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self.router.stop()
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)


def discover_backends(client, namespace: str, selector: Dict[str, str],
                      pool: str, port: int = 8080) -> List[Backend]:
    """Service discovery through the shared client: Services matching
    the selector labels become backends at their cluster DNS names
    (the RouterConfig engine-selector/decoder-selector contract)."""
    from ..core.k8s import Service
    out = []
    for svc in client.list(Service, namespace=namespace,
                           label_selector=selector):
        svc_port = port
        if svc.spec.ports:
            svc_port = svc.spec.ports[0].port
        out.append(Backend(
            f"http://{svc.metadata.name}.{svc.metadata.namespace}"
            f".svc.cluster.local:{svc_port}", pool))
    return out


def _parse_selector(s: str) -> Dict[str, str]:
    return dict(kv.split("=", 1) for kv in s.split(",") if "=" in kv)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ome-router")
    p.add_argument("--backend", action="append", default=[],
                   help="engine URL (repeatable); pool prefix with "
                        "'decoder=' routes to the decode pool")
    p.add_argument("--policy", default="cache_aware",
                   choices=("cache_aware", "round_robin", "random"))
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--bind", default="0.0.0.0")
    p.add_argument("--health-interval", type=float, default=10.0)
    p.add_argument("--engine-selector", default=None,
                   help="k8s label selector for engine Services "
                        "(k=v[,k=v]); requires --in-cluster/--kube-*")
    p.add_argument("--decoder-selector", default=None)
    p.add_argument("--namespace", default="default")
    p.add_argument("--kubeconfig", default=None)
    p.add_argument("--kube-server", default=None)
    p.add_argument("--in-cluster", action="store_true")
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    backends = []
    for spec in args.backend:
        # only known pool prefixes split — URLs may contain '='
        if spec.startswith("decoder="):
            backends.append(Backend(spec[len("decoder="):], "decoder"))
        elif spec.startswith("engine="):
            backends.append(Backend(spec[len("engine="):], "engine"))
        else:
            backends.append(Backend(spec, "engine"))
    if args.engine_selector or args.decoder_selector:
        from ..cmd.manager import build_client
        client = build_client(args)
        if args.engine_selector:
            backends += discover_backends(
                client, args.namespace,
                _parse_selector(args.engine_selector), "engine")
        if args.decoder_selector:
            backends += discover_backends(
                client, args.namespace,
                _parse_selector(args.decoder_selector), "decoder")
        log.info("discovered %d backends via selectors", len(backends))
    if not backends:
        p.error("at least one --backend or --engine-selector is required")
    router = Router(backends, policy=args.policy,
                    health_interval=args.health_interval)
    router.check_health_once()
    srv = RouterServer(router, host=args.bind, port=args.port).start()
    log.info("router on :%d over %d backends (policy=%s)", srv.port,
             len(backends), args.policy)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        srv.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
