"""Versioned-snapshot anti-entropy between router replicas.

The ingress tier is N stateless routers in front of one engine pool
(docs/router-ha.md). What must be shared is small and observational:

  * per-backend breaker/draining observations — replica A tripping a
    breaker should keep replica B from burning its own cb_threshold
    failures against the same dead backend;
  * the fleet prefix directory — which engine owns which prefix
    digest, so cache-aware peer hints work regardless of which
    router a request lands on.

What is deliberately NOT shared: backend membership (each replica's
--backend flags / autoscale registrations are its own), in-flight
accounting, retry budgets, metrics. Losing a router loses its
connections, never correctness — request durability lives in the
engine journal below.

Protocol: each replica keeps a monotonically-versioned snapshot of
its observations. Peers pull /gossip/state on the health-loop
cadence and merge with last-writer-wins per record, ordered by the
(wall-clock stamp, origin replica id) pair — a total order, so merge
is commutative, associative and idempotent (tests/test_gossip.py
proves it property-style), and any pull topology converges.

Clock note: breaker cooldowns are *monotonic*-clock deadlines, which
do not travel between processes. Snapshots therefore carry
``cb_open_remaining`` (seconds of cooldown left at serialization
time) and the merge re-anchors it onto the local monotonic clock.
LWW record stamps are wall-clock and only ORDER records; a skewed
clock ages one replica's observations, it never corrupts state.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.request
from typing import Dict, List, Optional

from .server import Backend, Router

log = logging.getLogger("ome.router.gossip")

# observation fields that constitute content: a change to any of them
# re-stamps the record (cb_open_remaining is volatile — it decays
# every second — so it is carried but never compared). "models" is the
# backend's /ready model advertisement — gossiping it lets a replica
# steer model-routed requests onto backends it has not probed yet
# (docs/model-fleet.md).
_OBS_FIELDS = ("pool", "healthy", "draining", "cb_state", "fails",
               "cb_trips", "models")


def lww_wins(a: Optional[dict], b: Optional[dict]) -> bool:
    """True when record `a` beats record `b` under last-writer-wins.
    Ordered by (stamp, origin): the stamp is the wall-clock second
    the observation changed; the origin replica id breaks exact
    ties deterministically. None always loses."""
    if a is None:
        return False
    if b is None:
        return True
    return ((a.get("stamp", 0.0), a.get("origin", "")) >
            (b.get("stamp", 0.0), b.get("origin", "")))


def merge_records(a: Optional[dict], b: Optional[dict]) -> Optional[dict]:
    """The newer of two records (pure; max under the LWW order)."""
    return a if lww_wins(a, b) else (b if b is not None else a)


def merge_backends(local: Dict[str, dict],
                   remote: Dict[str, dict]) -> Dict[str, dict]:
    """Per-URL LWW merge of backend-observation maps. Pure — the
    property tests drive this directly. Commutative and idempotent
    because each slot independently takes the max of a total order."""
    out = dict(local)
    for url, rec in remote.items():
        out[url] = merge_records(out.get(url), rec)
    return out


def merge_prefix(local: Dict[str, dict],
                 remote: Dict[str, dict]) -> Dict[str, dict]:
    """Per-digest LWW merge of prefix-directory maps (same algebra
    as merge_backends, keyed by digest instead of URL)."""
    return merge_backends(local, remote)


class GossipState:
    """One replica's versioned observation snapshot.

    The version is a monotonic counter bumped whenever snapshot
    CONTENT changes (a local observation re-stamped, or a merge that
    adopted remote records) — peers cache the last version they saw
    per replica and skip no-op merges."""

    def __init__(self, router: Router, replica_id: str):
        self.router = router
        self.replica_id = replica_id
        self._obs: Dict[str, dict] = {}
        self._prefix: Dict[str, dict] = {}
        self._version = 0
        self._seen_versions: Dict[str, int] = {}
        self._lock = threading.Lock()

    # -- local sampling ------------------------------------------------

    def _sample_backends(self) -> List[Backend]:
        with self.router._lock:
            return list(self.router.backends)

    def _refresh_local(self, now_wall: float) -> bool:
        """Fold the live Router state into the observation map. A
        record is re-stamped (stamp=now, origin=self) only when its
        content changed — an observation adopted from a peer keeps
        the peer's stamp until the LOCAL view diverges from it, so
        refresh never launders remote authorship. Caller holds
        self._lock."""
        changed = False
        live = {}
        for b in self._sample_backends():
            with b._lock:
                live[b.url] = {
                    "pool": b.pool, "healthy": b.healthy,
                    "draining": b.draining, "cb_state": b.cb_state,
                    "fails": b.fails, "cb_trips": b.cb_trips}
            # model advertisement rides the same record (leaf lock of
            # its own; taken after the backend lock is released)
            live[b.url]["models"] = sorted(
                self.router.model_map.models_of(b.url))
        for url, content in live.items():
            prev = self._obs.get(url)
            if prev is None or any(prev.get(f) != content[f]
                                   for f in _OBS_FIELDS):
                rec = dict(content)
                # a PRISTINE first record (healthy, closed, untouched
                # breaker) is a boot default, not an observation — it
                # gets stamp 0 so it can never outrank a peer's real
                # observation just because this replica booted later.
                # Any deviation (and any later change, including a
                # recovery back to pristine) earns a real stamp.
                pristine = (prev is None and content["healthy"]
                            and not content["draining"]
                            and content["cb_state"] == "closed"
                            and content["fails"] == 0
                            and content["cb_trips"] == 0
                            and not content["models"])
                rec["stamp"] = 0.0 if pristine else now_wall
                rec["origin"] = "" if pristine else self.replica_id
                self._obs[url] = rec
                changed = True
        for url in [u for u in self._obs if u not in live]:
            del self._obs[url]  # backend removed locally
            changed = True
        # prefix directory: last reporter wins a digest, same as the
        # directory itself; evicted digests drop out of the snapshot
        live_prefix = dict(self.router.prefix_directory.export())
        for digest, owner in live_prefix.items():
            prev = self._prefix.get(digest)
            if prev is None or prev.get("owner") != owner:
                self._prefix[digest] = {"owner": owner,
                                        "stamp": now_wall,
                                        "origin": self.replica_id}
                changed = True
        for digest in [d for d in self._prefix if d not in live_prefix]:
            del self._prefix[digest]
            changed = True
        return changed

    # -- snapshot / merge ----------------------------------------------

    def snapshot(self) -> dict:
        """The /gossip/state body. Backend records carry the
        non-volatile content plus cb_open_remaining computed fresh
        from the live breaker deadline (monotonic clocks do not
        travel; the peer re-anchors the remaining seconds)."""
        now_mono = time.monotonic()
        remaining = {}
        for b in self._sample_backends():
            with b._lock:
                remaining[b.url] = max(0.0, b.cb_open_until - now_mono) \
                    if b.cb_state in ("open", "half_open") else 0.0
        with self._lock:
            if self._refresh_local(time.time()):
                self._version += 1
            backends = {}
            for url, rec in self._obs.items():
                out = dict(rec)
                out["cb_open_remaining"] = round(
                    remaining.get(url, 0.0), 3)
                backends[url] = out
            return {"replica": self.replica_id,
                    "version": self._version,
                    "backends": backends,
                    "prefix": {d: dict(r)
                               for d, r in self._prefix.items()}}

    def merge(self, remote: dict) -> int:
        """Fold a peer snapshot in; returns the number of records
        adopted. Unknown backend URLs are skipped — membership is not
        gossiped, only observations about backends this replica
        already routes to. Adopted breaker state is applied to the
        live Backend (cooldown re-anchored onto the local monotonic
        clock, probe slot released — record_failure's probe-token
        idempotency absorbs the release racing a live probe)."""
        if not isinstance(remote, dict):
            return 0
        replica = remote.get("replica")
        version = remote.get("version")
        with self._lock:
            if replica is not None and \
                    self._seen_versions.get(replica) == version:
                return 0
            self._refresh_local(time.time())
            by_url = {b.url: b for b in self._sample_backends()}
            adopted = 0
            rbackends = remote.get("backends") or {}
            for url, rec in rbackends.items():
                if not isinstance(rec, dict):
                    continue
                b = by_url.get(url)
                if b is None:
                    continue
                if lww_wins(rec, self._obs.get(url)):
                    stored = {f: rec.get(f) for f in _OBS_FIELDS}
                    stored["stamp"] = rec.get("stamp", 0.0)
                    stored["origin"] = rec.get("origin", "")
                    self._obs[url] = stored
                    self._apply(b, rec)
                    # adopted model advertisements feed the model map
                    # (advertise ignores a record without the field)
                    self.router.model_map.advertise(
                        url, rec.get("models"))
                    adopted += 1
            rprefix = remote.get("prefix") or {}
            for digest, rec in rprefix.items():
                if not isinstance(rec, dict):
                    continue
                if lww_wins(rec, self._prefix.get(digest)):
                    owner = rec.get("owner")
                    if not isinstance(owner, str) or not owner:
                        continue
                    self._prefix[digest] = {
                        "owner": owner, "stamp": rec.get("stamp", 0.0),
                        "origin": rec.get("origin", "")}
                    self.router.prefix_directory.update(owner, [digest])
                    adopted += 1
            if replica is not None and isinstance(version, int):
                self._seen_versions[replica] = version
            if adopted:
                self._version += 1
            return adopted

    @staticmethod
    def _apply(b: Backend, rec: dict) -> None:
        state = rec.get("cb_state")
        if state not in ("closed", "half_open", "open"):
            return
        with b._lock:
            b.healthy = bool(rec.get("healthy", True))
            b.draining = bool(rec.get("draining", False))
            b.cb_state = state
            b.fails = int(rec.get("fails", 0))
            b.cb_trips = int(rec.get("cb_trips", 0))
            if state == "open":
                b.cb_open_until = time.monotonic() + float(
                    rec.get("cb_open_remaining") or 0.0)
            b._probe_inflight = False

    def stats(self) -> dict:
        with self._lock:
            return {"replica": self.replica_id,
                    "version": self._version,
                    "backends": len(self._obs),
                    "prefix": len(self._prefix),
                    "seen": dict(self._seen_versions)}


class GossipAgent:
    """Pull loop: fetch each peer's /gossip/state on the health-loop
    cadence and merge. Runs on a plain thread (urllib blocks) — the
    asyncio data path never touches the network here; it shares state
    through the same leaf locks the policy objects already use."""

    def __init__(self, state: GossipState, peers: List[str],
                 interval: float = 10.0, timeout: float = 5.0):
        self.state = state
        self.peers = [p.rstrip("/") for p in peers]
        self.interval = interval
        self.timeout = timeout
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        reg = state.router.registry
        self._c_pulls = reg.counter(
            "ome_router_gossip_pulls_total",
            "Anti-entropy snapshot pulls attempted against peers")
        self._c_pull_errors = reg.counter(
            "ome_router_gossip_pull_errors_total",
            "Anti-entropy pulls that failed (peer down or bad body)")
        self._c_merges = reg.counter(
            "ome_router_gossip_merges_total",
            "Peer snapshots merged that adopted at least one record")
        self._c_updates = reg.counter(
            "ome_router_gossip_record_updates_total",
            "Backend/prefix records adopted from peer snapshots")
        self._g_version = reg.gauge(
            "ome_router_gossip_version",
            "This replica's monotonic gossip snapshot version")
        self._g_peers = reg.gauge(
            "ome_router_gossip_peers",
            "Peer routers this replica pulls snapshots from")
        self._g_peers.set(len(self.peers))

    def pull_once(self) -> int:
        """One anti-entropy round: pull and merge every peer.
        Returns total records adopted (the convergence bound the
        chaos invariant asserts: one round suffices)."""
        total = 0
        for peer in self.peers:
            self._c_pulls.inc()
            try:
                with urllib.request.urlopen(
                        peer + "/gossip/state",
                        timeout=self.timeout) as resp:
                    snap = json.loads(resp.read() or b"{}")
            except Exception as e:
                self._c_pull_errors.inc()
                log.debug("gossip pull from %s failed: %s", peer, e)
                continue
            adopted = self.state.merge(snap)
            if adopted:
                self._c_merges.inc()
                self._c_updates.inc(adopted)
                total += adopted
        self._g_version.set(self.state.stats()["version"])
        return total

    def start(self) -> "GossipAgent":
        def loop():
            while not self._stop.wait(self.interval):
                self.pull_once()
        self._thread = threading.Thread(
            target=loop, name="router-gossip", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
