"""Declarative SLO specs: schema-versioned, per-class objectives.

The spec file (``config/slo.json``) mirrors the cost-table idiom: a
``schema_version`` gate so stale specs fail loudly, then plain data.
Each priority class carries a list of objectives; each objective is
either a **latency** objective (fraction of requests whose metric is
<= ``threshold_s`` must be >= ``target``) or an **availability**
objective (fraction of non-5xx/non-timeout outcomes >= ``target``).

Burn rate for an objective over a window W is
``bad_fraction(W) / (1 - target)`` — 1.0 means the error budget is
being consumed exactly at the rate that exhausts it over one
compliance window.  The maximum achievable burn is ``1/(1-target)``
(every request bad), which is why the alerting thresholds in
``sim_spec`` are lower than the SRE-workbook production values in
``config/slo.json``: a 14.4x page threshold is unreachable when the
target leaves a 5% budget.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from ..priority import PRIORITY_CLASSES

SLO_SCHEMA_VERSION = 1

# every objective name the evaluator knows how to source; latency
# names map to per-class engine histogram families (docs/slo.md)
OBJECTIVE_NAMES = ("ttft", "tpot", "e2e", "queue_wait", "availability")
OBJECTIVE_KINDS = ("latency", "availability")


@dataclass(frozen=True)
class BurnWindow:
    """One multi-window alert rule: page or warn severity."""
    long_s: float
    short_s: float
    burn_factor: float

    def validate(self, label: str) -> None:
        if not (self.long_s > self.short_s > 0):
            raise ValueError(
                f"slo window {label!r}: need long_s > short_s > 0, "
                f"got {self.long_s}/{self.short_s}")
        if self.burn_factor <= 0:
            raise ValueError(
                f"slo window {label!r}: burn_factor must be > 0")


@dataclass(frozen=True)
class Objective:
    name: str                       # one of OBJECTIVE_NAMES
    kind: str                       # "latency" | "availability"
    target: float                   # good fraction, in (0, 1)
    threshold_s: Optional[float] = None   # latency objectives only

    @property
    def budget(self) -> float:
        """Allowed bad fraction (1 - target)."""
        return 1.0 - self.target

    def validate(self, cls: str) -> None:
        if self.name not in OBJECTIVE_NAMES:
            raise ValueError(
                f"slo class {cls!r}: unknown objective {self.name!r} "
                f"(expected one of {OBJECTIVE_NAMES})")
        if self.kind not in OBJECTIVE_KINDS:
            raise ValueError(
                f"slo class {cls!r}: unknown kind {self.kind!r}")
        if (self.kind == "availability") != (self.name == "availability"):
            raise ValueError(
                f"slo class {cls!r}: objective {self.name!r} has "
                f"mismatched kind {self.kind!r}")
        if not (0.0 < self.target < 1.0):
            raise ValueError(
                f"slo class {cls!r}/{self.name}: target must be in "
                f"(0, 1), got {self.target}")
        if self.kind == "latency":
            if self.threshold_s is None or self.threshold_s <= 0:
                raise ValueError(
                    f"slo class {cls!r}/{self.name}: latency "
                    "objective needs threshold_s > 0")
        elif self.threshold_s is not None:
            raise ValueError(
                f"slo class {cls!r}/{self.name}: availability "
                "objective takes no threshold_s")


@dataclass(frozen=True)
class SLOSpec:
    compliance_window_s: float
    page: BurnWindow
    warn: BurnWindow
    classes: Dict[str, Tuple[Objective, ...]] = field(
        default_factory=dict)

    def validate(self) -> "SLOSpec":
        if self.compliance_window_s <= 0:
            raise ValueError("slo spec: compliance_window_s must "
                             "be > 0")
        self.page.validate("page")
        self.warn.validate("warn")
        if self.page.burn_factor <= self.warn.burn_factor:
            raise ValueError(
                "slo spec: page burn_factor must exceed warn "
                "burn_factor (page is the faster burn)")
        if not self.classes:
            raise ValueError("slo spec: no classes defined")
        for cls, objectives in self.classes.items():
            if cls not in PRIORITY_CLASSES:
                raise ValueError(
                    f"slo spec: unknown class {cls!r} (expected one "
                    f"of {PRIORITY_CLASSES})")
            if not objectives:
                raise ValueError(
                    f"slo class {cls!r}: no objectives")
            names = [o.name for o in objectives]
            if len(names) != len(set(names)):
                raise ValueError(
                    f"slo class {cls!r}: duplicate objective names")
            for obj in objectives:
                obj.validate(cls)
        return self

    def to_doc(self) -> dict:
        """Plain-JSON echo of the spec (reports embed this)."""
        classes = {}
        for cls in sorted(self.classes):
            objs = []
            for o in self.classes[cls]:
                d = {"name": o.name, "kind": o.kind,
                     "target": o.target}
                if o.threshold_s is not None:
                    d["threshold_s"] = o.threshold_s
                objs.append(d)
            classes[cls] = {"objectives": objs}
        return {
            "schema_version": SLO_SCHEMA_VERSION,
            "compliance_window_s": self.compliance_window_s,
            "windows": {
                "page": {"long_s": self.page.long_s,
                         "short_s": self.page.short_s,
                         "burn_factor": self.page.burn_factor},
                "warn": {"long_s": self.warn.long_s,
                         "short_s": self.warn.short_s,
                         "burn_factor": self.warn.burn_factor},
            },
            "classes": classes,
        }


def _window(doc: dict, label: str) -> BurnWindow:
    try:
        w = doc["windows"][label]
        return BurnWindow(long_s=float(w["long_s"]),
                          short_s=float(w["short_s"]),
                          burn_factor=float(w["burn_factor"]))
    except (KeyError, TypeError) as exc:
        raise ValueError(
            f"slo spec: bad or missing windows.{label}: {exc}")


def from_doc(doc: dict) -> SLOSpec:
    ver = doc.get("schema_version")
    if ver != SLO_SCHEMA_VERSION:
        raise ValueError(
            f"slo spec schema_version {ver!r} != "
            f"{SLO_SCHEMA_VERSION} — regenerate config/slo.json "
            "against the current spec format (docs/slo.md)")
    classes: Dict[str, Tuple[Objective, ...]] = {}
    for cls, body in dict(doc.get("classes") or {}).items():
        objs = []
        for o in (body or {}).get("objectives", []):
            objs.append(Objective(
                name=str(o.get("name")),
                kind=str(o.get("kind")),
                target=float(o.get("target", 0.0)),
                threshold_s=(float(o["threshold_s"])
                             if o.get("threshold_s") is not None
                             else None)))
        classes[cls] = tuple(objs)
    spec = SLOSpec(
        compliance_window_s=float(doc.get("compliance_window_s", 0)),
        page=_window(doc, "page"),
        warn=_window(doc, "warn"),
        classes=classes)
    return spec.validate()


def load(path: str) -> SLOSpec:
    """Load and validate a spec file (``config/slo.json``)."""
    with open(path) as fh:
        return from_doc(json.load(fh))


def sim_spec(compliance_window_s: float = 600.0) -> SLOSpec:
    """Simulator-scaled spec: short windows, reachable burn factors.

    The production spec's 14.4x page threshold needs a tight target
    (budget < 7%) to even be reachable; sim runs last minutes, not
    months, so this spec trades precision for speed: a 5% budget
    (target 0.95) with a 6x page burn over (60s, 5s) pages within
    one evaluation tick of a kill storm, and a 2x warn burn over
    (240s, 30s) catches slow degradation — while a fault-free steady
    run never alerts.
    """
    latency = lambda name, thr, target: Objective(
        name=name, kind="latency", target=target, threshold_s=thr)
    avail = Objective(name="availability", kind="availability",
                      target=0.95)
    # thresholds sit exactly on DEFAULT_BUCKETS bounds so count_le
    # is exact, which is what makes the sim<->replay parity contract
    # (+-1 request) hold without interpolation error
    objectives = (
        latency("ttft", 2.5, 0.9),
        latency("e2e", 10.0, 0.9),
        avail,
    )
    return SLOSpec(
        compliance_window_s=compliance_window_s,
        page=BurnWindow(long_s=60.0, short_s=5.0, burn_factor=6.0),
        warn=BurnWindow(long_s=240.0, short_s=30.0, burn_factor=2.0),
        classes={cls: objectives for cls in PRIORITY_CLASSES},
    ).validate()
