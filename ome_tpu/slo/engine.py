"""Deterministic SLO evaluator: attainment, budget, burn alerts.

The engine consumes per-(class, objective) good/total event deltas —
the fleet rollup feeds it from merged histogram/counter windows, the
replay client feeds it from client-observed results — and produces:

* **attainment** — good/total over the rolling compliance window;
* **error budget** — consumed = bad / (total * (1 - target)); 1.0
  means the window's allowance is spent;
* **burn rates** — bad_fraction(W) / (1 - target) over each of the
  four alerting windows (page long/short, warn long/short);
* **alert state** — the SRE-workbook multi-window multi-burn-rate
  policy: page when BOTH page windows burn >= the page factor, else
  warn when both warn windows burn >= the warn factor, else ok. A
  transition into warn/page appends a timestamped event to
  ``events`` and bumps ``ome_slo_alerts_total``.

Everything is driven by the **injected clock** — the identical code
runs on wall time in the router and on virtual time in the
simulator — and every emitted float is rounded so fixed-seed sim
runs produce byte-identical reports.
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

from ..priority import PRIORITY_CLASSES
from . import spec as spec_mod
from .spec import SLOSpec

# label-value vocabularies, module-level literals so the
# metrics-label-cardinality lint can prove every .labels() site
# bounded; OBJECTIVE_NAMES mirrors spec.OBJECTIVE_NAMES (asserted
# below) because the lint only trusts same-file constants
OBJECTIVE_NAMES = ("ttft", "tpot", "e2e", "queue_wait",
                   "availability")
BURN_WINDOW_NAMES = ("page_long", "page_short", "warn_long",
                     "warn_short")
ALERT_SEVERITIES = ("warn", "page")
ALERT_LEVELS = {"ok": 0, "warn": 1, "page": 2}

assert OBJECTIVE_NAMES == spec_mod.OBJECTIVE_NAMES


class _Series:
    """Rolling (t, good, total) deltas for one (class, objective)."""

    def __init__(self) -> None:
        self.points: Deque[Tuple[float, float, float]] = deque()
        self.state = "ok"

    def prune(self, horizon: float) -> None:
        while self.points and self.points[0][0] < horizon:
            self.points.popleft()

    def sums(self, since: float) -> Tuple[float, float]:
        good = total = 0.0
        for t, g, n in reversed(self.points):
            if t < since:
                break
            good += g
            total += n
        return good, total


class SLOEngine:
    def __init__(self, spec: SLOSpec,
                 clock: Callable[[], float],
                 registry=None):
        self.spec = spec
        self.clock = clock
        self.registry = registry
        self.events: List[dict] = []
        self._series: Dict[Tuple[str, str], _Series] = {
            (cls, obj.name): _Series()
            for cls, objectives in spec.classes.items()
            for obj in objectives}
        self._build_metrics(registry)

    # -- metrics ---------------------------------------------------
    def _build_metrics(self, registry) -> None:
        if registry is None:
            self._g_attain = self._g_budget = self._g_burn = None
            self._g_state = self._c_alerts = None
            self._c_good = self._c_events = self._c_evals = None
            return
        R = registry

        def _children(fam):
            return {(cls, obj): fam.labels(
                **{"class": cls, "objective": obj})
                for cls in PRIORITY_CLASSES
                for obj in OBJECTIVE_NAMES}

        g_attain = R.gauge(
            "ome_slo_attainment_ratio",
            "Good/total over the rolling compliance window "
            "(1.0 when the window holds no traffic)",
            labelnames=("class", "objective"))
        g_budget = R.gauge(
            "ome_slo_error_budget_remaining_ratio",
            "1 - bad/(total * (1 - target)) over the compliance "
            "window; <= 0 means the budget is exhausted",
            labelnames=("class", "objective"))
        g_state = R.gauge(
            "ome_slo_alert_state",
            "Current alert severity (0 = ok, 1 = warn, 2 = page)",
            labelnames=("class", "objective"))
        c_good = R.counter(
            "ome_slo_good_events_total",
            "SLO-good events ingested by the evaluator",
            labelnames=("class", "objective"))
        c_events = R.counter(
            "ome_slo_events_total",
            "All events ingested by the evaluator (good + bad)",
            labelnames=("class", "objective"))
        self._g_attain = _children(g_attain)
        self._g_budget = _children(g_budget)
        self._g_state = _children(g_state)
        self._c_good = _children(c_good)
        self._c_events = _children(c_events)
        g_burn = R.gauge(
            "ome_slo_burn_rate",
            "Error-budget burn rate bad_fraction/(1-target) per "
            "alerting window (1.0 = budget spent exactly over one "
            "compliance window)",
            labelnames=("class", "objective", "window"))
        self._g_burn = {
            (cls, obj, w): g_burn.labels(
                **{"class": cls, "objective": obj, "window": w})
            for cls in PRIORITY_CLASSES
            for obj in OBJECTIVE_NAMES
            for w in BURN_WINDOW_NAMES}
        c_alerts = R.counter(
            "ome_slo_alerts_total",
            "Alert-state transitions into warn/page",
            labelnames=("class", "objective", "severity"))
        self._c_alerts = {
            (cls, obj, sev): c_alerts.labels(
                **{"class": cls, "objective": obj, "severity": sev})
            for cls in PRIORITY_CLASSES
            for obj in OBJECTIVE_NAMES
            for sev in ALERT_SEVERITIES}
        self._c_evals = R.counter(
            "ome_slo_evaluations_total",
            "Evaluator passes over every (class, objective) series")

    # -- ingest ----------------------------------------------------
    def observe(self, cls: str, objective: str,
                good: float, total: float) -> None:
        """Record ``total`` new events, ``good`` of them good, for
        one (class, objective) at the current clock instant.
        Unknown pairs (not in the spec) are ignored."""
        series = self._series.get((cls, objective))
        if series is None or total <= 0:
            return
        good = max(0.0, min(good, total))
        series.points.append((self.clock(), good, total))
        if self._c_good is not None:
            self._c_good[(cls, objective)].inc(good)
            self._c_events[(cls, objective)].inc(total)

    # -- evaluate --------------------------------------------------
    def _burn(self, series: _Series, now: float, window_s: float,
              budget: float) -> float:
        good, total = series.sums(now - window_s)
        if total <= 0:
            return 0.0
        return ((total - good) / total) / budget

    def evaluate(self) -> Dict[str, dict]:
        """One evaluation pass; returns the per-class report dict
        (deterministic: sorted keys, rounded floats)."""
        now = self.clock()
        spec = self.spec
        report: Dict[str, dict] = {}
        for cls in sorted(spec.classes):
            cls_report = {}
            for obj in spec.classes[cls]:
                series = self._series[(cls, obj.name)]
                series.prune(now - spec.compliance_window_s)
                good, total = series.sums(now - spec.compliance_window_s)
                budget = obj.budget
                attainment = (round(good / total, 6)
                              if total > 0 else None)
                consumed = (round((total - good) / (total * budget), 6)
                            if total > 0 else 0.0)
                remaining = round(1.0 - consumed, 6)
                burns = {
                    "page_long": round(self._burn(
                        series, now, spec.page.long_s, budget), 6),
                    "page_short": round(self._burn(
                        series, now, spec.page.short_s, budget), 6),
                    "warn_long": round(self._burn(
                        series, now, spec.warn.long_s, budget), 6),
                    "warn_short": round(self._burn(
                        series, now, spec.warn.short_s, budget), 6),
                }
                pf, wf = spec.page.burn_factor, spec.warn.burn_factor
                if (burns["page_long"] >= pf
                        and burns["page_short"] >= pf):
                    state = "page"
                elif (burns["warn_long"] >= wf
                        and burns["warn_short"] >= wf):
                    state = "warn"
                else:
                    state = "ok"
                if state != series.state and state != "ok":
                    self.events.append({
                        "t": round(now, 6), "class": cls,
                        "objective": obj.name, "severity": state,
                        "burn_long": burns["page_long"
                                           if state == "page"
                                           else "warn_long"],
                        "burn_short": burns["page_short"
                                            if state == "page"
                                            else "warn_short"],
                        "budget_consumed": consumed,
                        "budget_remaining": remaining,
                    })
                    if self._c_alerts is not None:
                        self._c_alerts[(cls, obj.name, state)].inc()
                series.state = state
                if self._g_attain is not None:
                    key = (cls, obj.name)
                    self._g_attain[key].set(
                        1.0 if attainment is None else attainment)
                    self._g_budget[key].set(remaining)
                    self._g_state[key].set(ALERT_LEVELS[state])
                    for w, v in burns.items():
                        self._g_burn[(cls, obj.name, w)].set(v)
                cls_report[obj.name] = {
                    "good": round(good, 6),
                    "total": round(total, 6),
                    "attainment": attainment,
                    "target": obj.target,
                    "budget_consumed": consumed,
                    "budget_remaining": remaining,
                    "burn": burns,
                    "alert_state": state,
                }
            report[cls] = cls_report
        if self._c_evals is not None:
            self._c_evals.inc()
        return report

    def max_burn(self) -> float:
        """Fastest page-window long burn across every series — the
        optional autoscale pressure input (docs/autoscaling.md)."""
        now = self.clock()
        worst = 0.0
        for (cls, name), series in self._series.items():
            for obj in self.spec.classes.get(cls, ()):
                if obj.name != name:
                    continue
                worst = max(worst, self._burn(
                    series, now, self.spec.page.long_s, obj.budget))
        return round(worst, 6)

    def alert_state(self) -> Dict[str, str]:
        """{'class/objective': state} snapshot, sorted keys."""
        return {f"{cls}/{name}": s.state
                for (cls, name), s in sorted(self._series.items())}
