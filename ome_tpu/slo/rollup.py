"""Fleet rollup: scrape every backend, merge, evaluate, serve.

The rollup loop runs inside the router (a daemon thread on wall
time) and inside the simulator (an event-loop tick on virtual time)
over the SAME code path: each tick it fetches every registered
backend's /metrics through the injected ``fetch_fn`` (a
``SharedScraper`` when the autoscale controller also scrapes, so
each backend is fetched once per tick), merges the per-class latency
histograms bucket-wise across engines — re-basing per engine
incarnation so a mid-window restart never mixes pre- and
post-restart counters — reads the router's own per-class outcome
counters for availability, and feeds the deltas to the
``SLOEngine``.  ``report()`` is the body of ``GET /slo`` and of the
sim report's ``slo`` section.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, Iterable, Optional

from ..autoscale import scrape
from .engine import SLOEngine
from .spec import SLOSpec

log = logging.getLogger("ome.slo")

# objective name -> (histogram family, has per-class children).
# tpot has no per-class family yet, so every class reads the global
# distribution — documented in docs/slo.md.
LATENCY_FAMILIES = {
    "ttft": ("ome_engine_class_ttft_seconds", True),
    "queue_wait": ("ome_engine_class_queue_wait_seconds", True),
    "e2e": ("ome_engine_class_e2e_seconds", True),
    "tpot": ("ome_engine_tpot_seconds", False),
}

# the gauge sim engines expose so the rollup can re-base windows on
# restart; real engines do not expose it (incarnation stays None and
# the counts-went-backwards check covers restarts-from-zero)
INCARNATION_GAUGE = "ome_sim_engine_incarnation"

OUTCOME_FAMILY = "ome_router_class_outcomes_total"


class FleetRollup:
    def __init__(self, spec: SLOSpec,
                 clock: Callable[[], float],
                 fetch_fn: Callable[[str], Dict[str, float]],
                 backends_fn: Callable[[], Iterable[dict]],
                 registry=None,
                 local_samples_fn: Optional[
                     Callable[[], Dict[str, float]]] = None):
        self.spec = spec
        self.clock = clock
        self.fetch_fn = fetch_fn
        self.backends_fn = backends_fn
        self.local_samples_fn = local_samples_fn
        self.engine = SLOEngine(spec, clock, registry=registry)
        self.scrapes = 0
        self.scrape_errors = 0
        if registry is not None:
            self._c_scrapes = registry.counter(
                "ome_slo_scrapes_total",
                "Backend /metrics fetches issued by the SLO rollup")
            self._c_scrape_errors = registry.counter(
                "ome_slo_scrape_errors_total",
                "Failed backend fetches in the SLO rollup")
        else:
            self._c_scrapes = self._c_scrape_errors = None
        # one histogram window per (class, latency objective); the
        # global-family objectives (tpot) share one window per class
        # name anyway so per-class budgets still apply
        self._windows: Dict[tuple, scrape.HistogramWindow] = {}
        for cls, objectives in spec.classes.items():
            for obj in objectives:
                if obj.kind != "latency":
                    continue
                family, per_class = LATENCY_FAMILIES[obj.name]
                labels = {"class": cls} if per_class else None
                self._windows[(cls, obj.name)] = \
                    scrape.HistogramWindow(family, labels=labels,
                                           clock=clock)
        # availability from the router's own outcome counters:
        # ok/error deltas per class
        self._avail: Dict[tuple, scrape.CounterWindow] = {}
        for cls, objectives in spec.classes.items():
            if not any(o.kind == "availability" for o in objectives):
                continue
            for res in ("ok", "error"):
                self._avail[(cls, res)] = scrape.CounterWindow(
                    OUTCOME_FAMILY,
                    label_filter={"class": cls, "result": res})
        self._known: set = set()
        self._last_eval: Dict[str, dict] = {}
        self._last_at: Optional[float] = None

    def tick(self) -> None:
        """One rollup pass: scrape, merge, evaluate."""
        backends = list(self.backends_fn() or [])
        urls = [b.get("url") for b in backends if b.get("url")]
        gone = self._known - set(urls)
        for url in gone:
            for w in self._windows.values():
                w.forget(url)
        self._known = set(urls)
        for url in urls:
            try:
                samples = self.fetch_fn(url)
            except OSError:
                self.scrape_errors += 1
                if self._c_scrape_errors is not None:
                    self._c_scrape_errors.inc()
                for w in self._windows.values():
                    w.forget(url)
                continue
            self.scrapes += 1
            if self._c_scrapes is not None:
                self._c_scrapes.inc()
            incarnation = samples.get(INCARNATION_GAUGE)
            for w in self._windows.values():
                w.update(url, samples, incarnation=incarnation)
        for (cls, name), w in self._windows.items():
            merged = w.merged()
            if not merged:
                continue
            total = merged[-1][1]
            if total <= 0:
                continue
            threshold = next(
                o.threshold_s for o in self.spec.classes[cls]
                if o.name == name)
            good = scrape.count_le(merged, threshold)
            self.engine.observe(cls, name, good, total)
        if self._avail and self.local_samples_fn is not None:
            samples = self.local_samples_fn()
            for w in self._avail.values():
                w.update("local", samples)
            for cls in self.spec.classes:
                ok_w = self._avail.get((cls, "ok"))
                err_w = self._avail.get((cls, "error"))
                if ok_w is None:
                    continue
                good = ok_w.total()
                total = good + err_w.total()
                if total > 0:
                    self.engine.observe(cls, "availability",
                                        good, total)
        self._last_eval = self.engine.evaluate()
        self._last_at = round(self.clock(), 6)

    def max_burn(self) -> float:
        return self.engine.max_burn()

    def report(self) -> dict:
        """Deterministic report dict: the ``GET /slo`` body and the
        sim report's ``slo`` section (last completed tick)."""
        return {
            "at": self._last_at,
            "spec": self.spec.to_doc(),
            "classes": self._last_eval,
            "alerts": list(self.engine.events),
            "scrapes": self.scrapes,
            "scrape_errors": self.scrape_errors,
        }


def start_thread(rollup: FleetRollup, interval: float,
                 stop_event: Optional[threading.Event] = None
                 ) -> threading.Event:
    """The router side of the sim-vs-real parity contract: a daemon
    thread ticking the rollup on wall time (the simulator schedules
    ``rollup.tick`` on its virtual event loop instead). Returns the
    stop event; set it to end the loop."""
    stop = stop_event or threading.Event()

    def loop():
        while not stop.wait(interval):
            try:
                rollup.tick()
            except Exception:
                log.exception("slo rollup tick failed")

    threading.Thread(target=loop, daemon=True,
                     name="slo-rollup").start()
    return stop
