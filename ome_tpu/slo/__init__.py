"""Fleet SLO engine: error budgets and burn-rate alerting.

Declarative per-class objectives (config/slo.json) evaluated over
rolling windows by a deterministic engine on an injected clock, so
the identical code runs on wall time in the router and on virtual
time in the simulator.  docs/slo.md covers the spec format, the
multi-window multi-burn-rate alert policy, and the fleet rollup.
"""

from .spec import SLOSpec, Objective, BurnWindow, load, sim_spec
from .engine import SLOEngine
from .rollup import FleetRollup

__all__ = [
    "SLOSpec", "Objective", "BurnWindow", "load", "sim_spec",
    "SLOEngine", "FleetRollup",
]
